"""Speculative decoding: prompt-lookup drafts verified in one parallel
pass. The contract is absolute: greedy outputs are identical to
vanilla decode — speculation only changes how many passes they take.
"""

import time

import numpy as np
import pytest

from gofr_tpu.serving.engine import EngineConfig, SamplingParams
from gofr_tpu.serving.glue import demo_llama_engine

# a strongly repetitive prompt: prompt-lookup drafting thrives on it
PATTERN = [11, 22, 33, 44] * 12


def _cfg(**kw):
    base = dict(max_batch=2, max_seq=256, prefill_buckets=(64,), seed=9)
    base.update(kw)
    return EngineConfig(**base)


def _run(engine, prompt, n=24, temperature=0.0):
    engine.start()
    try:
        req = engine.submit_sync(prompt, SamplingParams(
            temperature=temperature, max_new_tokens=n))
        assert req.error is None, req.error
        return list(req.generated), dict(engine.stats)
    finally:
        engine.stop()


def test_greedy_tokens_identical_to_vanilla():
    vanilla, _ = _run(demo_llama_engine(_cfg()), PATTERN)
    spec, stats = _run(demo_llama_engine(_cfg(speculative=True)), PATTERN)
    assert spec == vanilla
    assert stats["spec_passes"] > 0


def test_paged_layout_matches_too():
    base = _cfg(kv_layout="paged", page_size=16)
    vanilla, _ = _run(demo_llama_engine(base), PATTERN)
    spec, stats = _run(
        demo_llama_engine(_cfg(kv_layout="paged", page_size=16,
                               speculative=True)), PATTERN)
    assert spec == vanilla
    assert stats["spec_passes"] > 0


def test_oracle_draft_accepts_and_saves_passes():
    """A perfect draft (the model's own continuation) must be fully
    accepted: same tokens, strictly fewer verify passes than tokens."""
    n = 24
    vanilla, _ = _run(demo_llama_engine(_cfg()), PATTERN, n=n)

    engine = demo_llama_engine(_cfg(speculative=True))
    future = {"tokens": vanilla}

    def oracle(req):
        done = len(req.generated)
        return future["tokens"][done:done + engine.config.spec_draft]

    engine._draft_proposals = oracle
    spec, stats = _run(engine, PATTERN, n=n)
    assert spec == vanilla
    assert stats["spec_accepted"] > 0
    # every pass lands spec_draft+1 tokens: far fewer passes than
    # tokens (vanilla takes ceil(n/decode_steps_per_pass) SCANNED
    # passes of 8 sequential steps; spec verifies in parallel)
    assert stats["spec_passes"] <= 2 + n // (engine.config.spec_draft + 1)


def test_mixed_greedy_and_sampled_slots():
    """A sampled request sharing the batch with a speculating greedy
    one: both complete with exact budgets; the greedy one still
    matches vanilla."""
    vanilla, _ = _run(demo_llama_engine(_cfg()), PATTERN, n=16)
    engine = demo_llama_engine(_cfg(speculative=True))
    engine.start()
    try:
        greedy = engine.submit(PATTERN, SamplingParams(
            temperature=0.0, max_new_tokens=16))
        sampled = engine.submit(list(np.random.RandomState(1)
                                     .randint(3, 200, size=20)),
                                SamplingParams(temperature=0.9,
                                               max_new_tokens=16))
        deadline = time.time() + 120
        while time.time() < deadline and not all(
                r.finished_at is not None or r.error
                for r in (greedy, sampled)):
            time.sleep(0.01)
        assert greedy.error is None and sampled.error is None
        assert list(greedy.generated) == vanilla
        assert len(sampled.generated) == 16
    finally:
        engine.stop()


def test_non_repetitive_prompt_just_decodes():
    """No n-gram matches -> no drafts -> pure vanilla path, still
    correct."""
    prompt = list(np.random.RandomState(4).randint(3, 200, size=40))
    vanilla, _ = _run(demo_llama_engine(_cfg()), prompt, n=8)
    spec, stats = _run(demo_llama_engine(_cfg(speculative=True)),
                       prompt, n=8)
    assert spec == vanilla


def test_cancel_during_speculation_retires_promptly():
    """A cancelled request must stop consuming verify passes even when
    its repetitive context would keep producing drafts."""
    engine = demo_llama_engine(_cfg(speculative=True))
    engine.start()
    try:
        req = engine.submit(PATTERN, SamplingParams(
            temperature=0.0, max_new_tokens=4096))
        deadline = time.time() + 30
        while time.time() < deadline and not req.generated:
            time.sleep(0.01)
        engine.cancel(req)
        deadline = time.time() + 30
        while time.time() < deadline and req.finished_at is None:
            time.sleep(0.01)
        assert req.finished_at is not None
        assert len(req.generated) < 4096  # nowhere near the budget
        follow = engine.submit_sync([1, 2, 3], SamplingParams(
            temperature=0.0, max_new_tokens=2))
        assert follow.error is None
    finally:
        engine.stop()


def test_paged_speculation_under_pool_pressure():
    """Verify-pass headroom contends with other slots: preemption
    inside the spec pass must not crash the loop, and both requests
    complete with exact budgets."""
    engine = demo_llama_engine(_cfg(
        kv_layout="paged", page_size=8, kv_pages=14,
        speculative=True, max_seq=128, prefill_buckets=(64,)))
    engine.start()
    try:
        a = engine.submit(PATTERN, SamplingParams(
            temperature=0.0, max_new_tokens=12))
        b = engine.submit(PATTERN[:24], SamplingParams(
            temperature=0.0, max_new_tokens=12))
        deadline = time.time() + 120
        while time.time() < deadline and not all(
                r.finished_at is not None or r.error for r in (a, b)):
            time.sleep(0.02)
        assert a.error is None and b.error is None, (a.error, b.error)
        assert len(a.generated) == 12 and len(b.generated) == 12
        assert engine._failed is None
    finally:
        engine.stop()
