"""Fleet observability plane: metrics federation over heartbeats,
cross-host trace stitching, straggler detection, stall escalation.

The contract under test extends PR 3's invariant across hosts: every
fleet surface is host-side assembly of data the engine already records
— snapshots read on heartbeat threads, skew computed on the leader,
the watchdog polling ``health_check()`` — so the transfer-guard and
greedy bit-identity tests pass with ALL of it enabled.
"""

import json
import time

import jax
import pytest

from gofr_tpu.container.container import Container
from gofr_tpu.logging.logger import (MockLogger, clear_fleet_context,
                                     current_fleet_context,
                                     set_fleet_context)
from gofr_tpu.metrics.registry import (Manager, merge_snapshots,
                                       render_federated)
from gofr_tpu.serving.control_plane import (ControlPlaneLeader,
                                            FleetConfig, WorkerAgent,
                                            engine_fleet_sources)
from gofr_tpu.serving.engine import EngineConfig, SamplingParams
from gofr_tpu.serving.glue import demo_llama_engine
from gofr_tpu.serving.observability import FlightRecorder, StallWatchdog
from gofr_tpu.tracing.tracer import InMemoryExporter, Tracer

from .apputil import AppRunner


@pytest.fixture(autouse=True)
def _clean_fleet_context():
    """The fleet context is process-global by design — never let one
    test's host identity leak into another's log records."""
    clear_fleet_context()
    yield
    clear_fleet_context()


def make_leader(**kw):
    leader = ControlPlaneLeader(coordinator="10.0.0.1:8476", **kw)

    def build(app):
        leader.install(app)
    return leader, build


def parse_prom(text: str) -> dict[str, float]:
    """{'name{a="b"}': value} — labels kept verbatim."""
    out = {}
    for line in text.splitlines():
        if not line.strip() or line.startswith("#"):
            continue
        name, _, value = line.rpartition(" ")
        out[name] = float(value)
    return out


# ------------------------------------------------------ registry snapshot
def test_manager_snapshot_round_trips_all_kinds():
    m = Manager()
    m.new_counter("jobs_total", "jobs")
    m.new_gauge("temp", "temperature")
    m.new_histogram("lat", "latency", buckets=(0.1, 1.0))
    m.add_counter("jobs_total", 3, queue="a")
    m.set_gauge("temp", 21.5)
    m.record_histogram("lat", 0.05)
    m.record_histogram("lat", 2.0)
    snap = m.snapshot()
    fams = snap["metrics"]
    assert fams["jobs_total"]["kind"] == "counter"
    assert fams["jobs_total"]["series"] == [
        {"labels": {"queue": "a"}, "value": 3.0}]
    assert fams["temp"]["series"][0]["value"] == 21.5
    lat = fams["lat"]
    assert lat["buckets"] == [0.1, 1.0]
    assert lat["series"][0]["counts"] == [1, 1]
    assert lat["series"][0]["count"] == 2
    json.dumps(snap)  # must be wire-safe as-is


def test_merge_snapshots_counters_sum_gauges_keep_histograms_merge():
    def host_snap(jobs, temp, lat_counts, lat_sum, lat_n):
        return {"metrics": {
            "jobs_total": {"kind": "counter", "help": "j", "series": [
                {"labels": {}, "value": jobs}]},
            "temp": {"kind": "gauge", "help": "t", "series": [
                {"labels": {}, "value": temp}]},
            "lat": {"kind": "histogram", "help": "l",
                    "buckets": [0.1, 1.0],
                    "series": [{"labels": {}, "counts": lat_counts,
                                "sum": lat_sum, "count": lat_n}]},
        }}

    merged = merge_snapshots({
        "a": host_snap(3.0, 20.0, [1, 2], 1.5, 3),
        "b": host_snap(4.0, 30.0, [2, 2], 2.5, 4)})["metrics"]
    assert merged["jobs_total"]["series"] == [{"labels": {}, "value": 7.0}]
    # up/down counters render as gauges but SUM across hosts
    updown = {"metrics": {"inflight": {
        "kind": "gauge", "help": "i", "updown": True,
        "series": [{"labels": {}, "value": 2.0}]}}}
    updown2 = {"metrics": {"inflight": {
        "kind": "gauge", "help": "i", "updown": True,
        "series": [{"labels": {}, "value": 5.0}]}}}
    m2 = merge_snapshots({"a": updown, "b": updown2})["metrics"]
    assert m2["inflight"]["series"] == [{"labels": {}, "value": 7.0}]
    temps = {s["labels"]["host"]: s["value"]
             for s in merged["temp"]["series"]}
    assert temps == {"a": 20.0, "b": 30.0}
    lat = merged["lat"]["series"][0]
    assert lat["counts"] == [3, 4] and lat["count"] == 7
    assert lat["sum"] == pytest.approx(4.0)


def test_render_federated_labels_every_sample_one_family_header():
    snap = {"metrics": {"jobs_total": {
        "kind": "counter", "help": "j",
        "series": [{"labels": {}, "value": 5.0}]}}}
    snap2 = {"metrics": {"jobs_total": {
        "kind": "counter", "help": "j",
        "series": [{"labels": {}, "value": 7.0}]}}}
    text = render_federated(
        {"h1": snap, "h2": snap2},
        {"h1": {"host": "h1", "rank": "0"},
         "h2": {"host": "h2", "rank": "1"}})
    assert text.count("# TYPE jobs_total counter") == 1
    series = parse_prom(text)
    assert series['jobs_total{host="h1",rank="0"}'] == 5.0
    assert series['jobs_total{host="h2",rank="1"}'] == 7.0
    assert sum(series.values()) == 12.0


# ------------------------------------------------- bounded span exporter
def test_inmemory_exporter_bounded_with_drop_counter():
    exp = InMemoryExporter(max_spans=4)
    tracer = Tracer(exporter=exp)
    for i in range(10):
        tracer.start_span(f"s{i}").end()
    assert len(exp.spans) == 4
    assert exp.dropped == 6
    assert [s.name for s in exp.spans] == ["s6", "s7", "s8", "s9"]


# ------------------------------------------------- flight fleet summary
def test_flight_recorder_fleet_summary_percentiles():
    rec = FlightRecorder(size=64)
    t0 = time.time()
    for i in range(20):
        rec.record_pass("decode", dur=0.01 * (i + 1), occupancy=4,
                        queue_depth=i, tokens=8)
    s = rec.fleet_summary()
    assert s["pass_p50_s"] == pytest.approx(0.10, abs=0.02)
    assert s["pass_p95_s"] == pytest.approx(0.19, abs=0.02)
    assert s["occupancy_mean"] == 4
    assert s["queue_depth"] == 19
    assert s["passes_recorded"] == 20
    # tokens_per_s appears once the ring spans real wall time
    assert "by_kind" in s and s["by_kind"]["decode"] == 20
    assert time.time() - t0 < 5


# --------------------------------------------------- federation over HTTP
def test_heartbeat_carries_summary_and_metrics_to_fleet_views():
    leader, build = make_leader()
    with AppRunner(build=build) as runner:
        managers = {}
        agents = {}
        for host in ("host-a", "host-b"):
            m = Manager()
            m.new_counter("app_engine_preemptions", "p")
            m.add_counter("app_engine_preemptions",
                          3.0 if host == "host-a" else 4.0)
            m.new_gauge("app_engine_tokens_per_second", "tps")
            m.set_gauge("app_engine_tokens_per_second", 100.0)
            managers[host] = m
            agents[host] = WorkerAgent(
                f"http://127.0.0.1:{runner.port}", host_id=host,
                n_devices=1, heartbeat_interval_s=0.1,
                metrics_source=m.snapshot,
                summary_source=lambda h=host: {
                    "pass_p50_s": 0.01, "pass_p95_s": 0.02,
                    "occupancy_mean": 3.0, "queue_depth": 1,
                    "tokens_per_s": 120.0})
        for agent in agents.values():
            agent.join()
        for agent in agents.values():
            agent._heartbeat_once()

        # consolidated JSON view
        status, body = runner.get_json("/debug/fleet")
        assert status == 200
        fleet = body["data"]
        assert fleet["world_size"] == 2
        assert fleet["generation"] == 2
        assert fleet["hosts"]["host-a"]["rank"] == 0
        assert fleet["hosts"]["host-b"]["summary"]["pass_p95_s"] == 0.02
        assert fleet["hosts"]["host-a"]["federated"]
        assert fleet["fleet"]["pass_skew"] >= 1.0
        assert fleet["counter_totals"]["app_engine_preemptions"] == 7.0

        # federated Prometheus text: host/rank labels, counters sum
        status, _, data = runner.request("GET", "/control/fleet/metrics")
        assert status == 200
        text = data.decode()
        series = parse_prom(text)
        a = series['app_engine_preemptions{host="host-a",rank="0"}']
        b = series['app_engine_preemptions{host="host-b",rank="1"}']
        assert (a, b) == (3.0, 4.0)
        assert text.count("# TYPE app_engine_preemptions counter") == 1
        # per-host gauges stay per-host
        assert series[
            'app_engine_tokens_per_second{host="host-a",rank="0"}'] == 100.0
        # leader-computed fleet families ride the same scrape
        assert series.get("app_fleet_generation") == 2.0
        assert series.get("app_fleet_world_size") == 2.0
        assert "app_fleet_pass_skew" in series


def test_federation_off_keeps_heartbeats_lean():
    leader, build = make_leader(fleet=FleetConfig(federation=False))
    with AppRunner(build=build) as runner:
        m = Manager()
        m.new_counter("c", "c")
        agent = WorkerAgent(f"http://127.0.0.1:{runner.port}",
                            host_id="w", heartbeat_interval_s=0.1,
                            metrics_source=m.snapshot,
                            fleet=FleetConfig(federation=False))
        agent.join()
        agent._heartbeat_once()
        status, body = runner.get_json("/debug/fleet")
        assert not body["data"]["hosts"]["w"]["federated"]
        status, _, data = runner.request("GET", "/control/fleet/metrics")
        assert status == 200
        text = data.decode()
        # no federated worker series (the leader's own app_fleet_*
        # families, e.g. host-labeled heartbeat counts, still render)
        assert "# TYPE c counter" not in text
        assert 'host="w",rank=' not in text


# ------------------------------------------------------------ stragglers
def test_straggler_detection_flags_skewed_host_and_warns():
    log = MockLogger()
    leader, build = make_leader(logger=log,
                                fleet=FleetConfig(straggler_ratio=1.5))
    with AppRunner(build=build) as runner:
        p95 = {"fast-1": 0.010, "fast-2": 0.011, "slow": 0.200}
        agents = {}
        for host, v in p95.items():
            agents[host] = WorkerAgent(
                f"http://127.0.0.1:{runner.port}", host_id=host,
                heartbeat_interval_s=0.1,
                summary_source=lambda v=v: {"pass_p95_s": v,
                                            "occupancy_mean": 2.0})
            agents[host].join()
        for agent in agents.values():
            agent._heartbeat_once()
        status, body = runner.get_json("/debug/fleet")
        fleet = body["data"]["fleet"]
        assert fleet["stragglers"] == ["slow"]
        assert fleet["worst_host"] == "slow"
        assert fleet["pass_skew"] == pytest.approx(0.2 / 0.011, rel=0.01)
        assert fleet["straggler_ratio"] == pytest.approx(1 / 3, abs=0.01)
        # gauges on the leader's own metrics port
        metrics = leader.metrics
        assert metrics.get("app_fleet_pass_skew").get() > 1.5
        assert metrics.get("app_fleet_straggler_ratio").get() > 0
        warns = [ln for ln in log.lines
                 if "straggler" in str(ln.get("message", ""))]
        assert warns and warns[0]["host"] == "slow"
        # WARN fires once per episode, not on every heartbeat
        agents["slow"]._heartbeat_once()
        warns2 = [ln for ln in log.lines
                  if "straggler" in str(ln.get("message", ""))]
        assert len(warns2) == len(warns)


def test_signature_normalized_straggler_names_the_kernel():
    """With federated cost tables the leader compares hosts on the
    SAME dispatch signature: a host that is genuinely slow on a shared
    kernel is flagged (and the signature named), while a host whose
    p95 is fat only because it serves a heavier shape mix is NOT — the
    exact confusion the raw max/median-p95 heuristic can't avoid."""
    log = MockLogger()
    leader, build = make_leader(logger=log,
                                fleet=FleetConfig(straggler_ratio=1.5))
    summaries = {
        # the reference host: normal mix, normal costs
        "fast": {"pass_p95_s": 0.010, "occupancy_mean": 2.0,
                 "costs": {
                     "decode/0": {"kind": "decode", "n": 50,
                                  "mean_s": 0.010},
                     "prefill/8/1": {"kind": "prefill", "n": 9,
                                     "mean_s": 0.040}}},
        # fattest p95 in the fleet — but only because it serves the
        # long-context window; its SHARED signature costs are normal
        "heavy-mix": {"pass_p95_s": 0.200, "occupancy_mean": 2.0,
                      "costs": {
                          "decode/0": {"kind": "decode", "n": 50,
                                       "mean_s": 0.011},
                          "decode/2048": {"kind": "decode", "n": 40,
                                          "mean_s": 0.190}}},
        # modest p95, but 3x the fleet median on the shared decode
        # kernel — the actual straggler
        "slow-kernel": {"pass_p95_s": 0.033, "occupancy_mean": 2.0,
                        "costs": {
                            "decode/0": {"kind": "decode", "n": 50,
                                         "mean_s": 0.033},
                            "prefill/8/1": {"kind": "prefill", "n": 9,
                                            "mean_s": 0.041}}},
    }
    with AppRunner(build=build) as runner:
        agents = {}
        for host, summary in summaries.items():
            agents[host] = WorkerAgent(
                f"http://127.0.0.1:{runner.port}", host_id=host,
                heartbeat_interval_s=0.1,
                summary_source=lambda s=summary: s)
            agents[host].join()
        for agent in agents.values():
            agent._heartbeat_once()
        status, body = runner.get_json("/debug/fleet")
        fleet = body["data"]["fleet"]
        assert fleet["straggler_mode"] == "signature"
        assert fleet["stragglers"] == ["slow-kernel"]
        assert fleet["straggler_signatures"] == {
            "slow-kernel": "decode/0"}
        # decode/2048 has one reporter, so it never enters the compare
        assert "decode/2048" not in fleet["costs"]["signatures"]
        assert fleet["costs"]["signatures"]["decode/0"] == \
            pytest.approx(0.011)
        assert sorted(fleet["costs"]["hosts"]) == \
            ["fast", "heavy-mix", "slow-kernel"]
        # the WARN names the kernel, not just the host
        warns = [ln for ln in log.lines
                 if "straggler" in str(ln.get("message", ""))]
        assert warns and warns[0]["host"] == "slow-kernel"
        assert warns[0]["signature"] == "decode/0"


# ------------------------------------------------------- trace stitching
def test_control_rpcs_stitch_one_trace_across_hosts():
    leader, build = make_leader()
    worker_exp = InMemoryExporter()
    worker_tracer = Tracer(service_name="worker", exporter=worker_exp)
    runner = AppRunner(build=build,
                       config={"TRACE_EXPORTER": "memory"})
    with runner:
        agent = WorkerAgent(f"http://127.0.0.1:{runner.port}",
                            host_id="w0", heartbeat_interval_s=0.1,
                            tracer=worker_tracer)
        agent.join()
        agent._heartbeat_once()
        client_spans = [s for s in worker_exp.spans
                        if s.name.startswith("control.")]
        assert {s.name for s in client_spans} >= {"control.join",
                                                  "control.heartbeat"}
        leader_spans = runner.app.container.tracer.exporter.spans
        for client in client_spans:
            server = [s for s in leader_spans
                      if s.trace_id == client.trace_id]
            assert server, f"no leader span on trace of {client.name}"
            assert any(s.parent_id == client.span_id for s in server)


def test_fleet_context_enriches_spans_and_logs_after_join():
    leader, build = make_leader()
    with AppRunner(build=build) as runner:
        agent = WorkerAgent(f"http://127.0.0.1:{runner.port}",
                            host_id="ctx-host",
                            heartbeat_interval_s=0.1)
        agent.join()
        ctx = current_fleet_context()
        assert ctx["host_id"] == "ctx-host"
        assert ctx["rank"] == 0 and ctx["generation"] == 1
        # every span now carries the host identity as resource attrs
        exp = InMemoryExporter()
        tracer = Tracer(exporter=exp)
        tracer.start_span("anything").end()
        attrs = exp.spans[0].attributes
        assert attrs["host_id"] == "ctx-host" and attrs["rank"] == 0
        # explicit attributes win over the resource context
        tracer.start_span("x", attributes={"rank": 9}).end()
        assert exp.spans[1].attributes["rank"] == 9
        # ...and every log record next to trace_id/span_id
        log = MockLogger()
        log.info("hello")
        rec = log.lines[0]
        assert rec["host_id"] == "ctx-host"
        assert rec["rank"] == 0 and rec["generation"] == 1


# ------------------------------------------------------ stall escalation
def _stalled_engine():
    """An engine whose stall flag IS set: work waiting, loop silent."""
    eng = demo_llama_engine(EngineConfig(
        max_batch=2, max_seq=64, seed=0, stall_threshold_s=0.05,
        watchdog_interval_s=0))  # watchdog driven by hand in tests
    eng.submit([1, 2, 3], SamplingParams(max_new_tokens=4))
    eng._running = True            # loop "alive"...
    eng._last_beat = time.time() - 10.0  # ...but no pass for 10 s
    return eng


def test_watchdog_escalates_stall_once_per_episode():
    eng = _stalled_engine()
    log = MockLogger()
    eng.logger = log
    exp = InMemoryExporter()
    eng.tracer = Tracer(exporter=exp)
    m = Manager()
    eng.attach_metrics(m)
    dog = StallWatchdog(eng, interval_s=0.05)
    assert eng.health_check()["status"] == "DEGRADED"
    assert dog.check_once() is True
    assert dog.check_once() is False          # same episode: no re-fire
    assert eng.stats["stalls"] == 1
    assert m.get("app_engine_stalls").get() == 1.0
    assert any(s.name == "engine.stall" for s in exp.spans)
    dumped = [ln for ln in log.lines
              if "flight recorder" in str(ln.get("message", ""))]
    assert dumped, "flight recorder was not dumped on stall"
    # recovery re-arms the watchdog
    eng._last_beat = time.time()
    assert dog.check_once() is False
    eng._last_beat = time.time() - 10.0
    assert dog.check_once() is True
    assert eng.stats["stalls"] == 2
    eng._running = False


def test_degraded_heartbeat_evicts_and_survivors_rerank():
    leader, build = make_leader()
    with AppRunner(build=build) as runner:
        eng = _stalled_engine()
        health, summary, _ = engine_fleet_sources(eng)
        sick = WorkerAgent(f"http://127.0.0.1:{runner.port}",
                           host_id="a-sick", heartbeat_interval_s=0.1,
                           health_source=health, summary_source=summary)
        survivor = WorkerAgent(f"http://127.0.0.1:{runner.port}",
                               host_id="b-ok", heartbeat_interval_s=0.1)
        sick.join()
        survivor.join()
        assert survivor.assignment.rank == 1
        generation = leader.generation
        assert leader.metrics.get("app_fleet_world_size").get() == 2.0

        sick._heartbeat_once()   # gossips DEGRADED -> evicted NOW
        assert sick.assignment is None
        topo = leader.topology()
        assert topo["world_size"] == 1
        assert "a-sick" not in topo["members"]
        assert leader.generation == generation + 1
        # fleet counters moved through the transition
        assert leader.metrics.get("app_fleet_evictions").get(
            reason="degraded") == 1.0
        assert leader.metrics.get("app_fleet_generation").get() \
            == leader.generation
        assert leader.metrics.get("app_fleet_world_size").get() == 1.0
        # survivor re-ranks to 0 at its next heartbeat (elastic regen)
        survivor._heartbeat_once()
        assert survivor.assignment.rank == 0
        assert survivor.assignment.world_size == 1
        # the degraded agent does NOT thrash back in while unhealthy
        assert not sick._healthy()
        sick._running = True
        assert sick.assignment is None
        # ...but a recovered engine rejoins through the normal path
        eng._last_beat = time.time()
        assert sick._healthy()
        sick.join()
        assert leader.topology()["world_size"] == 2
        eng._running = False


def test_stalled_worker_end_to_end_watchdog_to_eviction():
    """The full escalation: watchdog flips health, the next heartbeat
    gossips DEGRADED, the leader evicts and re-ranks — no heartbeat
    silence involved."""
    leader, build = make_leader()
    with AppRunner(build=build) as runner:
        eng = _stalled_engine()
        log = MockLogger()
        eng.logger = log
        health, summary, _ = engine_fleet_sources(eng)
        agent = WorkerAgent(f"http://127.0.0.1:{runner.port}",
                            host_id="w-stall",
                            heartbeat_interval_s=0.1,
                            health_source=health,
                            summary_source=summary)
        other = WorkerAgent(f"http://127.0.0.1:{runner.port}",
                            host_id="w-live", heartbeat_interval_s=0.1)
        agent.join()
        other.join()
        dog = StallWatchdog(eng, interval_s=0.05)
        assert dog.check_once()          # dump + counter + span
        agent._heartbeat_once()          # DEGRADED rides the heartbeat
        assert agent.assignment is None  # evicted
        other._heartbeat_once()
        assert other.assignment.rank == 0
        assert other.assignment.world_size == 1
        assert any("flight recorder" in str(ln.get("message", ""))
                   for ln in log.lines)
        eng._running = False


# --------------------------------------- zero-perturbation, fleet edition
def test_steady_state_zero_h2d_with_full_fleet_plane_enabled():
    """The transfer-guard contract with the ENTIRE fleet plane on:
    federation heartbeats, fleet context, watchdog, summaries. Decode
    steady state still uploads nothing host->device."""
    leader, build = make_leader()
    with AppRunner(build=build) as runner:
        container = Container()
        container.register_framework_metrics()
        tracer = Tracer(exporter=InMemoryExporter())
        eng = demo_llama_engine(
            EngineConfig(max_batch=4, max_seq=256, seed=0,
                         watchdog_interval_s=0.05), tracer=tracer)
        eng.attach_metrics(container.metrics)
        health, summary, metrics_src = engine_fleet_sources(eng)
        agent = WorkerAgent(f"http://127.0.0.1:{runner.port}",
                            host_id="perturb-0",
                            heartbeat_interval_s=0.05,
                            health_source=health,
                            summary_source=summary,
                            metrics_source=metrics_src,
                            tracer=tracer)
        agent.start()                # heartbeats + federation on a thread
        dog = StallWatchdog(eng, interval_s=0.05)
        dog.start()                  # watchdog polling health
        try:
            params = SamplingParams(temperature=0.0, max_new_tokens=200)
            with tracer.start_span("parent"):
                reqs = [eng.submit([1 + i, 2, 3], params)
                        for i in range(3)]
            batch = eng.waiting.pop_batch(len(reqs), first_wait_s=0.5)
            assert batch and len(batch) == len(reqs)
            eng._admit_batch(batch)
            eng._collect_prefills()
            for _ in range(2):       # admission upload + use_prev flip
                eng._decode_step()
                eng._drain_pending()
            transfers = eng.stats["h2d_transfers"]
            with jax.transfer_guard_host_to_device("disallow"):
                for _ in range(3):
                    eng._decode_step()
                    eng._drain_pending()
                time.sleep(0.15)     # heartbeats + watchdog fire inside
            assert eng.stats["h2d_transfers"] == transfers
            assert agent.assignment is not None  # fleet plane was live
        finally:
            dog.stop()
            agent.stop()


@pytest.mark.parametrize("layout_kw", [
    {},
    {"kv_layout": "paged", "page_size": 16, "paged_attention": "view"},
])
def test_greedy_bit_identical_with_fleet_plane_enabled(layout_kw):
    prompts = [[5 + i, 2, 9] for i in range(3)]

    def run(eng, tracer=None):
        eng.start()
        sp = SamplingParams(temperature=0.0, max_new_tokens=24)
        reqs = [eng.submit(p, sp) for p in prompts]
        deadline = time.time() + 120
        while time.time() < deadline and any(
                r.finished_at is None and r.error is None for r in reqs):
            time.sleep(0.005)
        eng.stop()
        assert all(r.error is None for r in reqs)
        return [r.generated for r in reqs]

    bare = demo_llama_engine(EngineConfig(
        max_batch=4, max_seq=128, seed=11, watchdog_interval_s=0,
        **layout_kw))
    want = run(bare)

    leader, build = make_leader()
    with AppRunner(build=build) as runner:
        container = Container()
        container.register_framework_metrics()
        tracer = Tracer(exporter=InMemoryExporter())
        eng = demo_llama_engine(EngineConfig(
            max_batch=4, max_seq=128, seed=11,
            watchdog_interval_s=0.05, **layout_kw), tracer=tracer)
        eng.attach_metrics(container.metrics)
        health, summary, metrics_src = engine_fleet_sources(eng)
        agent = WorkerAgent(f"http://127.0.0.1:{runner.port}",
                            host_id="bits-0", heartbeat_interval_s=0.05,
                            health_source=health,
                            summary_source=summary,
                            metrics_source=metrics_src, tracer=tracer)
        agent.start()
        try:
            got = run(eng, tracer)
        finally:
            agent.stop()
        assert got == want


# ---------------------------------------------------- app-level wiring
def test_app_serve_fleet_leader_and_join_fleet():
    from gofr_tpu.app import App
    from gofr_tpu.config import DictConfig
    from gofr_tpu.serving.tokenizer import ByteTokenizer

    leader_holder = {}

    def build(app):
        leader_holder["leader"] = app.serve_fleet_leader(
            coordinator="127.0.0.1:9999", host_id="the-leader")

    with AppRunner(build=build) as runner:
        worker_app = App(config=DictConfig({
            "HTTP_PORT": "0", "METRICS_PORT": "0",
            "APP_NAME": "fleet-worker", "TRACE_EXPORTER": "memory",
            "GOFR_TELEMETRY": "false"}))
        eng = demo_llama_engine(EngineConfig(max_batch=2, max_seq=64,
                                             seed=0))
        worker_app.serve_model("llm", eng, ByteTokenizer())
        agent = worker_app.join_fleet(
            f"http://127.0.0.1:{runner.port}", host_id="app-worker",
            heartbeat_interval_s=0.1)
        # the app hooks start/stop engine+agent; drive both by hand here
        eng.start()
        try:
            agent.join()
            agent._heartbeat_once()
        finally:
            eng.stop()
        status, body = runner.get_json("/debug/fleet")
        host = body["data"]["hosts"]["app-worker"]
        assert host["status"] == "UP"
        assert "active_slots" in host["summary"]
        assert host["federated"]  # container manager snapshot attached
        status, _, data = runner.request("GET", "/control/fleet/metrics")
        assert 'host="app-worker"' in data.decode()
