"""Tenant usage metering, SLO burn-rate tracking, exemplar-linked
metrics (PR 5).

The invariant carried over from PRs 3-4: metering + SLO + exemplars
fully enabled add ZERO host->device transfers to steady-state decode
and change no generated token — everything is host arithmetic over
data the engine already collects at collect/retire.
"""

import json
import time

import jax
import pytest

from gofr_tpu.container.container import Container
from gofr_tpu.http.auth import (
    APIKeyAuthProvider,
    TenantResolver,
    credential_fingerprint,
    jwt_sign_hs256,
)
from gofr_tpu.logging import MockLogger
from gofr_tpu.metrics.registry import Manager as MetricsManager
from gofr_tpu.serving.engine import EngineConfig, SamplingParams
from gofr_tpu.serving.glue import demo_llama_engine
from gofr_tpu.serving.observability import (
    SLOConfig,
    SLOTracker,
    UsageLedger,
    parse_window,
)
from gofr_tpu.serving.tokenizer import ByteTokenizer
from gofr_tpu.tracing.tracer import InMemoryExporter, Tracer

from .apputil import AppRunner


def _run(eng, submits, n, *, timeout=120):
    """submits: list of (prompt, tenant). Returns the requests."""
    eng.start()
    sp = SamplingParams(temperature=0.0, max_new_tokens=n)
    reqs = [eng.submit(p, sp, tenant=t) for p, t in submits]
    deadline = time.time() + timeout
    while time.time() < deadline and any(
            r.finished_at is None and r.error is None for r in reqs):
        time.sleep(0.005)
    eng.stop()
    assert all(r.error is None for r in reqs), [r.error for r in reqs]
    return reqs


# ------------------------------------------------------ tenant resolution
class TestTenantResolver:
    def test_each_principal_shape(self):
        r = TenantResolver()
        assert r.resolve(None) == "anonymous"
        assert r.resolve({}) == "anonymous"
        assert r.resolve({"username": "alice"}) == "alice"
        assert r.resolve({"claims": {"org": "acme", "sub": "u1"}}) == "acme"
        assert r.resolve({"claims": {"sub": "u1"}}) == "u1"
        assert r.resolve({"api_key": "deadbeef0123"}) == "key-deadbeef0123"
        assert r.resolve({"tenant": "team-blue"}) == "team-blue"
        # unknown shape: a hashed bucket, never the raw repr
        label = r.resolve({"auth": "s3cr3t-token"})
        assert label.startswith("t-") and "s3cr3t" not in label

    def test_cardinality_hard_bound(self):
        r = TenantResolver(max_tenants=3)
        seen = {r.resolve({"username": f"u{i}"}) for i in range(3)}
        assert seen == {"u0", "u1", "u2"}
        # the 4th (and every later) new label collapses
        assert r.resolve({"username": "u3"}) == "other"
        assert r.resolve({"username": "u99"}) == "other"
        # already-seen labels keep resolving to themselves
        assert r.resolve({"username": "u1"}) == "u1"

    def test_labels_sanitized(self):
        r = TenantResolver()
        assert r.resolve({"username": 'ev"il\nname{x}'}) == "ev_il_name_x_"
        assert len(r.resolve({"username": "x" * 300})) == 64

    def test_api_key_provider_hashes_and_maps(self):
        provider = APIKeyAuthProvider(
            keys=["legacy-key"], key_names={"named-key": "team-blue"})

        class Req:
            def __init__(self, key):
                self._key = key

            def header(self, k):
                return self._key if k == "x-api-key" else ""

        named = provider.authenticate(Req("named-key"))
        assert named["tenant"] == "team-blue"
        assert named["api_key"] == credential_fingerprint("named-key")
        assert "named-key" not in json.dumps(named)
        legacy = provider.authenticate(Req("legacy-key"))
        assert legacy == {"api_key": credential_fingerprint("legacy-key")}
        assert provider.authenticate(Req("wrong")) is None


# --------------------------------------------------------- usage ledger
def test_ledger_device_time_shares_sum_to_busy_time():
    """Each pass's busy span splits across its occupied rows; summed
    back over the retired requests it reproduces the recorded pass
    time — device-time attribution conserves the total."""
    eng = demo_llama_engine(EngineConfig(max_batch=4, max_seq=128,
                                         seed=7))
    reqs = _run(eng, [([1 + i, 2, 3], f"t{i % 2}") for i in range(4)], 16)
    share_total = sum(r.device_s for r in reqs)
    pass_total = sum(p.get("dur", 0.0)
                     for p in eng.recorder.snapshot()["passes"])
    assert share_total > 0
    assert share_total <= pass_total * 1.05
    assert share_total >= pass_total * 0.75, (share_total, pass_total)
    # and the ledger accounted exactly what the requests accumulated
    roll = eng.usage_ledger.rollup()
    ledger_total = sum(t["device_s"] for t in roll["tenants"].values())
    assert ledger_total == pytest.approx(share_total, rel=1e-4)
    assert set(roll["tenants"]) == {"t0", "t1"}


def test_ledger_rollup_windows_and_status():
    ledger = UsageLedger()
    now = time.time()
    ledger.record(tenant="acme", status="ok", prompt_tokens=10,
                  completion_tokens=20, t=now - 600)
    ledger.record(tenant="acme", status="ok", prompt_tokens=1,
                  completion_tokens=2, t=now - 10)
    ledger.record(tenant="acme", status="error", prompt_tokens=3,
                  completion_tokens=0, t=now - 5)
    ledger.record(tenant="globex", status="ok", prompt_tokens=7,
                  completion_tokens=9, t=now - 5)
    total = ledger.rollup()
    assert total["tenants"]["acme"]["prompt_tokens"] == 14
    assert total["tenants"]["acme"]["requests"] == {"ok": 2, "error": 1}
    # 5-minute window drops the 10-minute-old event
    recent = ledger.rollup(window_s=300.0)
    assert recent["tenants"]["acme"]["prompt_tokens"] == 4
    assert recent["tenants"]["acme"]["requests"] == {"ok": 1, "error": 1}
    # tenant filter
    only = ledger.rollup(tenant="globex")
    assert set(only["tenants"]) == {"globex"}
    assert parse_window("5m") == 300.0
    with pytest.raises(ValueError):
        parse_window("soon")


def test_failed_submission_is_metered_as_error():
    eng = demo_llama_engine(EngineConfig(max_batch=2, max_seq=64))
    eng.stop()  # closes the waiting queue
    req = eng.submit([1, 2, 3], SamplingParams(max_new_tokens=4),
                     tenant="acme")
    assert req.error is not None
    roll = eng.usage_ledger.rollup(tenant="acme")
    assert roll["tenants"]["acme"]["requests"] == {"error": 1}
    assert roll["tenants"]["acme"]["completion_tokens"] == 0


# ------------------------------------------------------------------- SLO
class TestSLO:
    def test_burn_rate_math_on_synthetic_stream(self):
        cfg = SLOConfig(availability=0.99, windows=(60.0, 3600.0),
                        fast_burn=0.0, budget_window_s=3600.0)
        t0 = time.time()
        tracker = SLOTracker(cfg)
        # 40 old requests (2 bad) land only in the 1h window; 10 recent
        # (2 bad) land in both
        for i in range(40):
            tracker.record(good=i % 20 != 0, t=t0 - 600)
        for i in range(10):
            tracker.record(good=i % 5 != 0, t=t0 - 1)
        state = tracker.state()
        one_m, one_h = state["windows"]["1m"], state["windows"]["1h"]
        assert one_m["total"] == 10 and one_m["bad"] == 2
        assert one_m["error_rate"] == pytest.approx(0.2)
        assert one_m["burn_rate"] == pytest.approx(0.2 / 0.01)  # 20x
        assert one_h["total"] == 50 and one_h["bad"] == 4
        assert one_h["burn_rate"] == pytest.approx(0.08 / 0.01)
        # budget: 50 requests allow 0.5 errors, 4 burned -> deep red
        assert state["budget"]["remaining"] == -1.0  # clamped
        good_only = SLOTracker(cfg)
        for _ in range(100):
            good_only.record(good=True)
        assert good_only.state()["budget"]["remaining"] == 1.0

    def test_judge_thresholds(self):
        tracker = SLOTracker(SLOConfig(ttft_s=0.1, tpot_s=0.01,
                                       e2e_s=1.0))
        judge = tracker.judge
        assert judge(error=None, ttft_s=0.05, tpot_s=0.005, e2e_s=0.5)
        assert not judge(error="boom", ttft_s=0.05, tpot_s=0.005,
                         e2e_s=0.5)
        assert not judge(error=None, ttft_s=0.2, tpot_s=0.005, e2e_s=0.5)
        assert not judge(error=None, ttft_s=0.05, tpot_s=0.02, e2e_s=0.5)
        assert not judge(error=None, ttft_s=0.05, tpot_s=0.005, e2e_s=2.0)
        # None metrics (no tokens) never violate; None limits disable
        assert judge(error=None, ttft_s=None, tpot_s=None, e2e_s=0.5)
        lax = SLOTracker(SLOConfig(ttft_s=None, tpot_s=None, e2e_s=None))
        assert lax.judge(error=None, ttft_s=99, tpot_s=99, e2e_s=99)

    def test_fast_burn_warns_once_per_episode(self):
        logger = MockLogger()
        m = MetricsManager()
        m.new_gauge("app_slo_burn_rate", "x")
        m.new_gauge("app_slo_error_budget_remaining", "x")
        tracker = SLOTracker(
            SLOConfig(availability=0.9, windows=(0.5, 60.0),
                      fast_burn=5.0), metrics=m, logger=logger)
        for _ in range(5):
            tracker.record(good=False)  # burn 10x >= 5 -> trip
        warns = [ln for ln in logger.lines if ln["level"] == "WARN"]
        assert len(warns) == 1, "one WARN per episode, not per request"
        assert "fast burn" in warns[0]["message"]
        # gauges published
        assert m.get("app_slo_burn_rate").get(window="1m") > 0
        # episode ends (fast window empties), re-arms, trips again
        time.sleep(0.6)
        for _ in range(20):
            tracker.record(good=True)
        for _ in range(20):
            tracker.record(good=False)
        warns = [ln for ln in logger.lines if ln["level"] == "WARN"]
        assert len(warns) == 2


# -------------------------------------------------------------- exemplars
def test_exemplar_rendering_parity_and_capture():
    """Plain Prometheus output is byte-identical with exemplars stored
    or not; the OpenMetrics rendering carries them and terminates with
    # EOF."""
    bare = MetricsManager()
    bare.new_histogram("app_chat_e2e_seconds", "e2e", buckets=(0.1, 1))
    bare.record_histogram("app_chat_e2e_seconds", 0.05)

    with_ex = MetricsManager()
    with_ex.new_histogram("app_chat_e2e_seconds", "e2e", buckets=(0.1, 1))
    with_ex.record_histogram("app_chat_e2e_seconds", 0.05,
                             exemplar_trace_id="ab" * 16)
    assert bare.render_prometheus() == with_ex.render_prometheus()
    assert "trace_id" not in with_ex.render_prometheus()

    om = with_ex.render_openmetrics()
    assert f'# {{trace_id="{"ab" * 16}"}} 0.05' in om
    assert om.rstrip().endswith("# EOF")
    # the exemplar sits on the bucket the observation fell into
    line = next(ln for ln in om.splitlines() if "trace_id" in ln)
    assert 'le="0.1"' in line
    # no-exemplar managers still render valid OpenMetrics
    assert bare.render_openmetrics().rstrip().endswith("# EOF")


def test_exemplar_captured_from_active_span():
    """Histogram.record with no explicit trace id picks up the active
    request's trace (the contextvar the tracer middleware sets)."""
    tracer = Tracer(exporter=InMemoryExporter())
    m = MetricsManager()
    m.new_histogram("app_http_response", "h")
    with tracer.start_span("GET /x") as span:
        m.record_histogram("app_http_response", 0.02)
    om = m.render_openmetrics()
    assert f'trace_id="{span.trace_id}"' in om


# ---------------------------------------- zero-perturbation, all features
def test_steady_state_zero_h2d_with_metering_slo_exemplars_on():
    container = Container()
    container.register_framework_metrics()
    tracer = Tracer(exporter=InMemoryExporter())
    eng = demo_llama_engine(EngineConfig(max_batch=4, max_seq=256,
                                         seed=0), tracer=tracer)
    eng.attach_metrics(container.metrics)
    eng.slo = SLOTracker(SLOConfig(), metrics=container.metrics)
    params = SamplingParams(temperature=0.0, max_new_tokens=200)
    with tracer.start_span("parent"):
        reqs = [eng.submit([1 + i, 2, 3], params, tenant=f"t{i}")
                for i in range(3)]
    batch = eng.waiting.pop_batch(len(reqs), first_wait_s=0.5)
    assert batch and len(batch) == len(reqs)
    eng._admit_batch(batch)
    eng._collect_prefills()
    for _ in range(2):  # admission upload, then the use_prev flip
        eng._decode_step()
        eng._drain_pending()
    transfers = eng.stats["h2d_transfers"]
    with jax.transfer_guard_host_to_device("disallow"):
        for _ in range(3):
            eng._decode_step()
            eng._drain_pending()
    assert eng.stats["h2d_transfers"] == transfers
    # the metering plane observed those passes (device shares accrued)
    assert all(r.device_s > 0 for r in reqs)


@pytest.mark.parametrize("layout_kw", [
    {},
    {"kv_layout": "paged", "page_size": 16, "paged_attention": "view"},
])
def test_greedy_bit_identical_with_metering_slo_exemplars_on(layout_kw):
    prompts = [[5 + i, 2, 9] for i in range(3)]

    def cfg():
        return EngineConfig(max_batch=4, max_seq=128, seed=11,
                            **layout_kw)

    bare = demo_llama_engine(cfg())
    bare.usage_ledger = None  # truly bare: no metering at all
    want = [r.generated
            for r in _run(bare, [(p, None) for p in prompts], 24)]

    container = Container()
    container.register_framework_metrics()
    tracer = Tracer(exporter=InMemoryExporter())
    obs = demo_llama_engine(cfg(), tracer=tracer)
    obs.attach_metrics(container.metrics)
    obs.slo = SLOTracker(SLOConfig(), metrics=container.metrics)
    got = _run(obs, [(p, f"tenant-{i}") for i, p in enumerate(prompts)],
               24)
    assert [r.generated for r in got] == want
    # every tenant accounted, SLO fed, exemplar-capable series present
    assert set(obs.usage_ledger.rollup()["tenants"]) == \
        {f"tenant-{i}" for i in range(3)}
    assert obs.slo.state()["lifetime"]["total"] == 3
    assert container.metrics.get_histogram_count(
        "app_tenant_e2e_seconds", tenant="tenant-0") == 1


# ------------------------------------------------------------------- e2e
@pytest.fixture(scope="module")
def tenant_app():
    engine = demo_llama_engine(EngineConfig(max_batch=4, max_seq=128,
                                            seed=0))

    def build(app):
        app.enable_api_key_auth(key_names={"alpha-key": "team-alpha",
                                           "beta-key": "team-beta"})
        app.serve_model("llm", engine, ByteTokenizer())

    runner = AppRunner(build=build,
                       config={"TRACE_EXPORTER": "memory"})
    with runner as app:
        yield app


def _chat(app, key, prompt, n=6):
    status, _, data = app.request(
        "POST", "/chat",
        {"prompt": prompt, "max_tokens": n, "temperature": 0.0},
        headers={"X-Api-Key": key})
    assert status == 201, (status, data[:200])
    return json.loads(data)["data"]


def test_e2e_tenant_attribution_usage_and_slo(tenant_app):
    usages = [_chat(tenant_app, "alpha-key", "hello from alpha")["usage"],
              _chat(tenant_app, "alpha-key", "more alpha")["usage"],
              _chat(tenant_app, "beta-key", "hello from beta")["usage"]]
    assert [u["tenant"] for u in usages] == \
        ["team-alpha", "team-alpha", "team-beta"]
    # unauthenticated requests bounce (auth still enforced)
    status, _, _ = tenant_app.request(
        "POST", "/chat", {"prompt": "x", "max_tokens": 2})
    assert status == 401

    # /debug/usage totals == the sum of the chat responses' usage
    status, body = tenant_app.get_json("/debug/usage",
                                       headers={"X-Api-Key": "alpha-key"})
    assert status == 200
    tenants = body["data"]["llm"]["tenants"]
    for label in ("team-alpha", "team-beta"):
        want_prompt = sum(u["prompt_tokens"] for u in usages
                          if u["tenant"] == label)
        want_completion = sum(u["completion_tokens"] for u in usages
                              if u["tenant"] == label)
        assert tenants[label]["prompt_tokens"] == want_prompt, label
        assert tenants[label]["completion_tokens"] == want_completion
        assert tenants[label]["device_s"] > 0
    # tenant + window filters work
    status, body = tenant_app.get_json(
        "/debug/usage?tenant=team-beta&window=5m",
        headers={"X-Api-Key": "alpha-key"})
    assert status == 200
    assert set(body["data"]["llm"]["tenants"]) == {"team-beta"}

    # /debug/slo reports the tracked stream
    status, body = tenant_app.get_json("/debug/slo",
                                       headers={"X-Api-Key": "alpha-key"})
    assert status == 200
    slo = body["data"]["llm"]
    assert slo["lifetime"]["total"] >= 3
    assert "5m" in slo["windows"] and "1h" in slo["windows"]
    assert slo["budget"]["remaining"] == 1.0  # nothing failed

    # tenant-labeled series on /metrics; raw keys nowhere in sight
    _, _, data = tenant_app.request("GET", "/metrics",
                                    port=tenant_app.metrics_port)
    text = data.decode()
    assert 'app_tenant_requests{status="ok",tenant="team-alpha"} 2' in text
    assert 'tenant="team-beta"' in text
    assert "alpha-key" not in text and "beta-key" not in text


def test_e2e_openmetrics_exemplars_resolve_to_engine_traces(tenant_app):
    trace_id = "fe" * 16
    status, _, _ = tenant_app.request(
        "POST", "/chat",
        {"prompt": "exemplar probe", "max_tokens": 6, "temperature": 0.0},
        headers={"X-Api-Key": "alpha-key",
                 "traceparent": f"00-{trace_id}-{'cd' * 8}-01"})
    assert status == 201
    # plain scrape: classic text format, no exemplars
    _, headers, data = tenant_app.request("GET", "/metrics",
                                          port=tenant_app.metrics_port)
    assert "openmetrics" not in headers.get("Content-Type", "")
    assert "trace_id" not in data.decode()
    # negotiated scrape: exemplars + # EOF, same series
    _, headers, data = tenant_app.request(
        "GET", "/metrics", port=tenant_app.metrics_port,
        headers={"Accept": "application/openmetrics-text"})
    assert "application/openmetrics-text" in headers.get("Content-Type", "")
    om = data.decode()
    assert om.rstrip().endswith("# EOF")
    exemplar_ids = {seg.split('"')[1] for line in om.splitlines()
                    if "trace_id" in line
                    for seg in [line.split("trace_id=", 1)[1]]}
    assert trace_id in exemplar_ids
    # ...and that trace id resolves to a real engine.request span
    spans = tenant_app.app.container.tracer.exporter.spans
    assert any(s.name == "engine.request" and s.trace_id == trace_id
               for s in spans)
    # the engine.request span names the tenant
    span = next(s for s in spans if s.name == "engine.request"
                and s.trace_id == trace_id)
    assert span.attributes["tenant"] == "team-alpha"


def test_e2e_request_log_carries_tenant(tenant_app):
    """The logging middleware stamps the resolved tenant into the
    request log record (auth runs inside it, so the principal is on
    the request by the time the log line is built)."""
    from gofr_tpu.http.middleware import RequestLog, logging_middleware
    import asyncio

    resolver = tenant_app.app.container.tenant_resolver
    logger = MockLogger()

    class FakeReq:
        method, path, client_addr = "POST", "/chat", "1.2.3.4"
        auth_info = {"tenant": "team-alpha"}

    async def handler(request):
        from gofr_tpu.http.responder import ResponseData
        return ResponseData(status=200, body=b"{}")

    wrapped = logging_middleware(logger, tenant_resolver=resolver)(handler)
    asyncio.run(wrapped(FakeReq()))
    record = logger.lines[0]["message"]
    assert record["tenant"] == "team-alpha"
