"""Migration ledgers for the non-SQL store families the reference
migrates — cassandra/scylla (CQL), clickhouse, oracle, mongo, and
pub/sub topic-create — each store carrying its own ``gofr_migrations``
ledger over the in-repo clients (reference
pkg/gofr/migration/migration.go:137-235, cassandra.go, mongo.go;
VERDICT r4 #7)."""

from __future__ import annotations

import pytest

from gofr_tpu.config import DictConfig
from gofr_tpu.container.container import Container
from gofr_tpu.datasource.columnar import (
    Cassandra,
    Clickhouse,
    Oracle,
    ScyllaDB,
)
from gofr_tpu.migrations import Migrate, MigrationError, run


def make_container(**stores) -> Container:
    c = Container(config=DictConfig({}))
    for slot, store in stores.items():
        store.connect()
        setattr(c, slot, store)
    return c


LEDGER_Q = "SELECT version FROM gofr_migrations"


class TestCassandraMigrations:
    def test_ledger_and_order(self):
        c = make_container(cassandra=Cassandra())
        applied = run(c, {
            2: Migrate(up=lambda ds: ds.cassandra.exec(
                "INSERT INTO spans (id) VALUES (1)")),
            1: Migrate(up=lambda ds: ds.cassandra.exec(
                "CREATE TABLE spans (id BIGINT PRIMARY KEY)")),
        })
        assert applied == [1, 2]
        versions = [r["version"] for r in c.cassandra.query(LEDGER_Q)]
        assert sorted(versions) == [1, 2]
        assert len(c.cassandra.query("SELECT * FROM spans")) == 1

    def test_rerun_is_idempotent(self):
        c = make_container(cassandra=Cassandra())
        migrations = {1: Migrate(up=lambda ds: ds.cassandra.exec(
            "CREATE TABLE t1 (id BIGINT PRIMARY KEY)"))}
        assert run(c, migrations) == [1]
        assert run(c, migrations) == []

    def test_scylla_uses_same_cql_ledger(self):
        c = make_container(scylladb=ScyllaDB())
        assert run(c, {1: Migrate(up=lambda ds: ds.scylladb.exec(
            "CREATE TABLE s1 (id BIGINT PRIMARY KEY)"))}) == [1]
        assert [r["version"] for r in c.scylladb.query(LEDGER_Q)] == [1]


class TestClickhouseMigrations:
    def test_ledger_and_data(self):
        c = make_container(clickhouse=Clickhouse())
        applied = run(c, {
            1: Migrate(up=lambda ds: ds.clickhouse.exec(
                "CREATE TABLE events (ts BIGINT, kind TEXT)")),
            2: Migrate(up=lambda ds: ds.clickhouse.exec(
                "INSERT INTO events (ts, kind) VALUES (1, 'boot')")),
        })
        assert applied == [1, 2]
        assert [r["version"] for r in sorted(
            c.clickhouse.query(LEDGER_Q), key=lambda r: r["version"])] \
            == [1, 2]
        assert c.clickhouse.query("SELECT kind FROM events")[0]["kind"] \
            == "boot"

    def test_rerun_is_idempotent(self):
        c = make_container(clickhouse=Clickhouse())
        migrations = {7: Migrate(up=lambda ds: ds.clickhouse.exec(
            "CREATE TABLE e2 (id BIGINT)"))}
        assert run(c, migrations) == [7]
        assert run(c, migrations) == []


class TestOracleMigrations:
    def test_ledger_and_data(self):
        c = make_container(oracle=Oracle())
        applied = run(c, {
            1: Migrate(up=lambda ds: ds.oracle.exec(
                "CREATE TABLE accounts (id BIGINT PRIMARY KEY, "
                "balance BIGINT)")),
        })
        assert applied == [1]
        assert [r["version"] for r in c.oracle.query(LEDGER_Q)] == [1]
        assert run(c, {1: Migrate(up=lambda ds: None)}) == []


class TestMongoMigrations:
    @pytest.fixture()
    def mongo(self):
        from gofr_tpu.datasource.mongo_wire import (
            MiniMongoServer,
            MongoWire,
        )
        server = MiniMongoServer()
        server.start()
        client = MongoWire(host="127.0.0.1", port=server.port,
                           database="t")
        client.connect()
        yield client
        client.close()
        server.close()

    def test_document_ledger(self, mongo):
        c = Container(config=DictConfig({}))
        c.mongo = mongo
        applied = run(c, {
            1: Migrate(up=lambda ds: ds.mongo.insert_one(
                "users", {"name": "ada"})),
            2: Migrate(up=lambda ds: ds.mongo.insert_one(
                "users", {"name": "lin"})),
        })
        assert applied == [1, 2]
        ledger = mongo.find("gofr_migrations")
        assert sorted(d["version"] for d in ledger) == [1, 2]
        assert run(c, {2: Migrate(up=lambda ds: None)}) == []
        assert len(mongo.find("users")) == 2


class TestCrossStoreLedgers:
    def test_shared_last_version_across_stores(self):
        """One run over sql+cassandra records both ledgers; a later
        run against the same container skips what either ledger has
        (reference records every initialized store's ledger)."""
        from gofr_tpu.datasource.sql import SQL
        sql = SQL()
        sql.connect()
        c = make_container(cassandra=Cassandra())
        c.sql = sql
        assert run(c, {1: Migrate(up=lambda ds: ds.cassandra.exec(
            "CREATE TABLE x1 (id BIGINT PRIMARY KEY)"))}) == [1]
        assert [r["version"] for r in c.cassandra.query(LEDGER_Q)] == [1]
        assert [r["version"] for r in sql.query(LEDGER_Q)] == [1]
        assert run(c, {1: Migrate(up=lambda ds: None)}) == []

    def test_pubsub_topic_create_with_cassandra_ledger(self):
        """Topic-create migrations (reference migration/pubsub.go)
        tracked by a non-SQL ledger."""
        from gofr_tpu.pubsub.inmemory import InMemoryBroker
        c = make_container(cassandra=Cassandra())
        c.pubsub = InMemoryBroker()
        assert run(c, {1: Migrate(
            up=lambda ds: ds.pubsub.create_topic("orders"))}) == [1]
        assert "orders" in c.pubsub.topics
        assert run(c, {1: Migrate(up=lambda ds: None)}) == []

    def test_statement_store_failure_keeps_ledger_clean(self):
        """A failing up() must leave no ledger record for that
        version, so a rerun retries it."""
        c = make_container(cassandra=Cassandra())

        def boom(ds):
            raise RuntimeError("mid-migration crash")

        with pytest.raises(RuntimeError):
            run(c, {1: Migrate(up=boom)})
        assert c.cassandra.query(LEDGER_Q) == []
        assert run(c, {1: Migrate(up=lambda ds: ds.cassandra.exec(
            "CREATE TABLE ok (id BIGINT PRIMARY KEY)"))}) == [1]
