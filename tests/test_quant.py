"""Weight-only int8 quantization: numerics bounds, llama forward
parity, and the serving engine running quantized end-to-end."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from gofr_tpu.models.llama import (LlamaConfig, llama_init, llama_prefill)
from gofr_tpu.ops.quant import (qgather, qmatmul, quantize_int4,
                                quantize_int8,
                                quantize_llama_int8, quantized_bytes)


def test_quantize_roundtrip_error_bounded():
    w = jax.random.normal(jax.random.key(0), (64, 48), jnp.float32)
    qw = quantize_int8(w, axis=0)
    deq = qw["q"].astype(jnp.float32) * qw["s"].astype(jnp.float32)
    # symmetric rounding: error <= half a quantization step per element
    step = np.asarray(qw["s"], np.float32)        # [1, 48]
    err = np.abs(np.asarray(deq) - np.asarray(w))
    assert (err <= step / 2 + 1e-6).all()


def test_qmatmul_close_to_dense():
    k1, k2 = jax.random.split(jax.random.key(1))
    x = jax.random.normal(k1, (8, 64), jnp.float32)
    w = jax.random.normal(k2, (64, 32), jnp.float32)
    want = np.asarray(x @ w)
    got = np.asarray(qmatmul(x, quantize_int8(w, axis=0)))
    denom = np.abs(want).mean()
    assert np.abs(got - want).mean() / denom < 0.01   # ~1% relative


def test_qgather_scales_rows():
    table = jax.random.normal(jax.random.key(2), (10, 16), jnp.float32)
    qt = quantize_int8(table, axis=1)              # per-row scales
    idx = jnp.asarray([3, 7])
    got = np.asarray(qgather(qt, idx, jnp.float32))
    want = np.asarray(table[idx])
    assert np.abs(got - want).max() <= np.asarray(qt["s"]).max() / 2 + 1e-6


@pytest.mark.parametrize("tie", [True, False])
def test_llama_logits_parity(tie):
    config = LlamaConfig.tiny().scaled(tie_embeddings=tie)
    params = llama_init(jax.random.key(3), config)
    qparams = quantize_llama_int8(params)
    tokens = jnp.asarray([[5, 9, 2, 31, 7, 12]], jnp.int32)
    logits, _ = llama_prefill(params, tokens, config,
                              implementation="xla")
    qlogits, _ = llama_prefill(qparams, tokens, config,
                               implementation="xla")
    a = np.asarray(logits, np.float64).ravel()
    b = np.asarray(qlogits, np.float64).ravel()
    corr = np.corrcoef(a, b)[0, 1]
    assert corr > 0.995, corr


def test_quantized_bytes_shrink():
    config = LlamaConfig.tiny()
    params = llama_init(jax.random.key(4), config)
    before = quantized_bytes(params)               # f32 tiny weights
    after = quantized_bytes(quantize_llama_int8(params))
    assert after < before / 2                       # int8 + small scales


def test_engine_serves_quantized():
    import time

    from gofr_tpu.serving.engine import EngineConfig, SamplingParams
    from gofr_tpu.serving.glue import llama_engine

    config = LlamaConfig.tiny()
    params = llama_init(jax.random.key(5), config)
    engine = llama_engine(params, config,
                          EngineConfig(max_batch=2, max_seq=128, seed=6),
                          implementation="xla", quantize="int8")
    engine.start()
    reqs = [engine.submit([3 + i, 1, 4], SamplingParams(
        temperature=0.0, max_new_tokens=8)) for i in range(3)]
    deadline = time.time() + 120
    while time.time() < deadline and any(
            r.finished_at is None and r.error is None for r in reqs):
        time.sleep(0.01)
    engine.stop()
    assert all(r.error is None for r in reqs)
    assert all(len(r.generated) == 8 for r in reqs)
    # greedy determinism holds WITHIN the quantized model
    again = llama_engine(params, config,
                         EngineConfig(max_batch=2, max_seq=128, seed=6),
                         implementation="xla", quantize="int8")
    again.start()
    rep = again.submit([3, 1, 4], SamplingParams(temperature=0.0,
                                                 max_new_tokens=8))
    deadline = time.time() + 120
    while time.time() < deadline and rep.finished_at is None \
            and rep.error is None:
        time.sleep(0.01)
    again.stop()
    assert rep.generated == reqs[0].generated


def test_engine_quantize_rejects_unknown():
    from gofr_tpu.serving.engine import EngineConfig
    from gofr_tpu.serving.glue import llama_engine

    config = LlamaConfig.tiny()
    params = llama_init(jax.random.key(7), config)
    with pytest.raises(ValueError, match="int8"):
        llama_engine(params, config, EngineConfig(max_batch=2),
                     quantize="fp4")


def test_int8_composes_with_native_paged_kernel():
    """int8 weights + the native paged decode path (row writes through
    the block table, ragged kernel in interpret mode) must match the
    int8 slot-layout engine greedily — protects the best-known TPU
    serving composition (paged kernel + int8)."""
    import time

    from gofr_tpu.serving.engine import EngineConfig, SamplingParams
    from gofr_tpu.serving.glue import llama_engine

    config = LlamaConfig.tiny()
    params = llama_init(jax.random.key(11), config)

    def run(**extra):
        eng = llama_engine(params, config,
                           EngineConfig(max_batch=2, max_seq=128, seed=9,
                                        **extra),
                           implementation="xla", quantize="int8")
        eng.start()
        reqs = [eng.submit([5 + i, 2, 9], SamplingParams(
            temperature=0.0, max_new_tokens=8)) for i in range(2)]
        deadline = time.time() + 120
        while time.time() < deadline and any(
                r.finished_at is None and r.error is None for r in reqs):
            time.sleep(0.01)
        eng.stop()
        assert all(r.error is None for r in reqs), [r.error for r in reqs]
        assert all(len(r.generated) == 8 for r in reqs)  # really finished
        return [r.generated for r in reqs]

    want = run()
    got = run(kv_layout="paged", page_size=16,
              paged_attention="interpret")
    assert got == want


def test_int4_roundtrip_bounds():
    w = jax.random.normal(jax.random.key(4), (32, 16), jnp.float32)
    qw = quantize_int4(w, axis=0)
    assert str(qw["q"].dtype) == "int4"
    deq = np.asarray(qw["q"].astype(jnp.float32) * qw["s"])
    # full-range scheme (scale = amax/8): error <= half a step per
    # element, except weights in the top half-step below +amax — the
    # exact-amax guard clips their unrepresentable +8 down to +7, so
    # their error is bounded by one step instead
    step = np.broadcast_to(np.asarray(qw["s"])[0], w.shape)
    err = np.abs(deq - np.asarray(w))
    clipped = np.asarray(w) > 7.5 * step - 1e-6
    assert (err[~clipped] <= step[~clipped] / 2 + 1e-6).all()
    assert (err <= step + 1e-6).all()


def test_int4_uses_full_range():
    """scale = amax/8 must actually reach the -8 code point (the old
    [-7, 7] scheme wasted it) and pin +amax to +7."""
    w = jnp.asarray([[-1.0, -0.97, 0.5, 1.0]], jnp.float32).T  # [4, 1]
    qw = quantize_int4(w, axis=0)
    q = np.asarray(qw["q"].astype(jnp.int8)).ravel()
    assert q.min() == -8          # -amax -> -8 exactly
    assert q.max() == 7           # +amax clipped by the guard
    assert np.isclose(np.asarray(qw["s"]).ravel()[0], 1.0 / 8.0)


def test_int4_engine_serves_and_is_deterministic():
    from gofr_tpu.serving.engine import EngineConfig, SamplingParams
    from gofr_tpu.serving.glue import llama_engine

    config = LlamaConfig.tiny()
    params = llama_init(jax.random.key(2), config)

    def run():
        eng = llama_engine(params, config,
                           EngineConfig(max_batch=2, max_seq=64, seed=3),
                           implementation="xla", quantize="int4")
        eng.start()
        req = eng.submit_sync([4, 2, 9], SamplingParams(
            temperature=0.0, max_new_tokens=8))
        eng.stop()
        assert req.error is None, req.error
        assert len(req.generated) == 8
        return req.generated

    assert run() == run()  # greedy determinism within the int4 model


def test_int4_quarter_bytes():
    config = LlamaConfig.tiny()
    params = llama_init(jax.random.key(0), config)
    from gofr_tpu.ops.quant import quantize_llama_int4
    before = quantized_bytes(params)
    after = quantized_bytes(quantize_llama_int4(params))
    # tiny config is f32 (4 B/param): int4 storage should be ~1/8th
    # plus scale overhead
    assert after < before / 6


def test_quantized_bytes_dtype_detection():
    """Explicit dtype comparison, not substring matching: int4 AND
    uint4 count the packed half byte; everything else counts its
    itemsize."""
    tree = {"a": jnp.zeros((10,), jnp.int4),
            "b": jnp.zeros((10,), jnp.uint4),
            "c": jnp.zeros((10,), jnp.int8),
            "d": jnp.zeros((10,), jnp.float32)}
    assert quantized_bytes(tree) == int(10 * 0.5 + 10 * 0.5 + 10 + 40)


def test_quantized_bytes_covers_kv_pool_tree():
    """The engine's kv_bytes accounting is quantized_bytes over the
    (k_cache, v_cache) pytree — the paged pool's {"q", "s"} split must
    sum codes + per-row scales, and the bf16 pool its plain array."""
    from gofr_tpu.ops.paged_kv import quantize_pool
    l, h, np_, pg, d = 2, 2, 4, 8, 16
    plain = jnp.zeros((l, h, np_, pg, d), jnp.bfloat16)
    assert quantized_bytes((plain, plain)) == 2 * l * h * np_ * pg * d * 2
    qp = quantize_pool(plain)
    want = l * h * np_ * pg * (d + 4)          # int8 codes + f32 scale
    assert quantized_bytes((qp, qp)) == 2 * want
