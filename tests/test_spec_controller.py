"""Speculation policy layer (serving/spec.py): draft trees, the
incremental n-gram index, the goodput-priced controller — and the
engine integration points that keep controller/index state honest
across preemption, restart and recovery."""

import numpy as np
import pytest

from gofr_tpu.serving.spec import (MAX_TREE_NODES, DraftTree, NgramIndex,
                                   SpecController, build_draft_tree)


# ------------------------------------------------------------ DraftTree
class TestDraftTree:
    def test_topological_packing_and_masks(self):
        t = DraftTree.root(7)
        a = t.add(0, 1)
        b = t.add(a, 2)
        c = t.add(0, 3)          # sibling fork off the root
        assert t.parents == [0, 0, a, 0]
        assert t.depths == [0, 1, 2, 1]
        for i in range(1, t.n_nodes):
            assert t.parents[i] < i  # parent index < child index
        # masks: ancestor-or-self bits over the node index
        assert t.masks[0] == 0b0001
        assert t.masks[a] == 0b0011
        assert t.masks[b] == 0b0111
        assert t.masks[c] == 0b1001  # root + itself, NOT the other fork
        assert t.path_to(b) == [0, a, b]
        assert t.path_to(c) == [0, c]

    def test_from_chain_is_the_historical_shape(self):
        t = DraftTree.from_chain(9, [4, 5, 6])
        assert t.tokens == [9, 4, 5, 6]
        assert t.parents == [0, 0, 1, 2]
        assert t.depths == [0, 1, 2, 3]
        # a chain's masks are exactly the causal window
        assert t.masks == [0b1, 0b11, 0b111, 0b1111]

    def test_capacity_is_the_bitmask_width(self):
        t = DraftTree.root(0)
        for i in range(MAX_TREE_NODES - 1):
            t.add(0, i)
        with pytest.raises(ValueError, match="exceeds"):
            t.add(0, 99)

    def test_trie_merge_shares_prefixes(self):
        t = build_draft_tree(0, [[1, 2, 3], [1, 2, 9], [5]])
        # "1 2" shared: 1 root + 3 + 1 + 1 nodes, not 1 + 3 + 3 + 1
        assert t.n_nodes == 6
        assert t.max_depth == 3

    def test_trie_merge_stops_silently_at_cap(self):
        chains = [[i, i + 100] for i in range(40)]
        t = build_draft_tree(0, chains, max_nodes=8)
        assert t.n_nodes == 8


# ----------------------------------------------------------- NgramIndex
class TestNgramIndex:
    def _naive(self, toks, n, depth, branches):
        """The old O(context) rescan, generalized to k branches."""
        if len(toks) < n:
            return []
        tail = toks[-n:]
        out, seen = [], set()
        for pos in range(len(toks) - n - 1, -1, -1):
            if toks[pos:pos + n] == tail:
                cont = toks[pos + n:pos + n + depth]
                if not cont or cont[0] in seen:
                    continue
                seen.add(cont[0])
                out.append(cont)
                if len(out) >= branches:
                    break
        return out

    def test_incremental_matches_naive_rescan(self):
        rng = np.random.RandomState(0)
        toks = list(rng.randint(0, 6, size=400))  # small alphabet:
        idx = NgramIndex(3)                       # plenty of repeats
        idx.extend(toks[:100])
        for i in range(100, len(toks)):
            idx.extend([toks[i]])
            got = idx.propose(4, 2)
            want = self._naive(toks[:i + 1], 3, 4, 2)
            assert got == want, i

    def test_skips_the_suffix_own_occurrence(self):
        idx = NgramIndex(2)
        idx.extend([1, 2, 3, 1, 2])  # the tail "1 2" occurs at 0 and 3
        assert idx.propose(2, 2) == [[3, 1]]  # pos 3 has no continuation

    def test_distinct_first_tokens(self):
        idx = NgramIndex(2)
        idx.extend([1, 2, 7, 0, 1, 2, 7, 9, 1, 2])
        chains = idx.propose(3, 4)
        firsts = [c[0] for c in chains]
        assert len(firsts) == len(set(firsts)) == 1  # both start 7
        assert chains[0][0] == 7

    def test_zero_depth_or_branches_proposes_nothing(self):
        idx = NgramIndex(2)
        idx.extend([1, 2, 1, 2])
        assert idx.propose(0, 2) == []
        assert idx.propose(2, 0) == []


# -------------------------------------------------------- SpecController
def _calibrated(ctrl, *, spt=1e-3, rc=1e-5):
    ctrl.note_decode(spt * 10, 10)     # sec/token = spt
    ctrl.note_verify(rc * 20, 4, 5)    # row cost = rc
    return ctrl


class TestSpecController:
    def test_optimistic_bootstrap_drafts_full_depth(self):
        ctrl = SpecController(2, draft=4, branches=2)
        assert ctrl.plan(0) == (4, 2)          # uncalibrated: go fit
        assert ctrl.accept_rate() == 1.0       # gauge stays in [0, 1]

    def test_cheap_verify_keeps_full_depth(self):
        ctrl = _calibrated(SpecController(2, draft=4, branches=2))
        assert ctrl.plan(0) == (4, 2)

    def test_expensive_verify_shrinks_depth(self):
        # rows nearly as expensive as a decoded token: with a mediocre
        # accept EWMA only shallow drafts still pay
        ctrl = _calibrated(SpecController(2, draft=4, branches=2),
                           spt=1e-3, rc=4e-4)
        ctrl.accept[0] = 0.85
        depth, branches = ctrl.plan(0)
        assert 0 < depth < 4
        assert branches == 2

    def test_worthless_drafting_plans_zero(self):
        ctrl = _calibrated(SpecController(2, draft=4, branches=2),
                           spt=1e-3, rc=9e-4)
        ctrl.accept[0] = 0.3  # 0.3 * 1e-3 < 2 * 9e-4 already at d=1
        assert ctrl.plan(0) == (0, 0)

    def test_collapse_disables_then_probe_reenables(self):
        ctrl = _calibrated(SpecController(1, draft=4, branches=2,
                                          accept_floor=0.2,
                                          probe_interval=4))
        for _ in range(12):                    # EWMA collapses
            ctrl.note_result(0, 4, 0)
        assert ctrl.disabled[0]
        plans = [ctrl.plan(0) for _ in range(4)]
        assert plans[:3] == [(0, 0)] * 3       # idle until the probe
        assert plans[3] == (1, 1)              # single-node probe
        ctrl.note_result(0, 1, 1)              # the probe survives
        assert not ctrl.disabled[0]
        assert ctrl.plan(0)[0] > 0

    def test_dead_probe_stays_disabled(self):
        ctrl = _calibrated(SpecController(1, draft=4, branches=1,
                                          accept_floor=0.2,
                                          probe_interval=2))
        for _ in range(12):
            ctrl.note_result(0, 4, 0)
        assert ctrl.disabled[0]
        ctrl.note_result(0, 1, 0)              # probe dies
        assert ctrl.disabled[0]

    def test_reset_slot_restores_optimism(self):
        ctrl = _calibrated(SpecController(1, draft=4, branches=2))
        for _ in range(12):
            ctrl.note_result(0, 4, 0)
        assert ctrl.disabled[0]
        ctrl.reset_slot(0)
        assert not ctrl.disabled[0]
        assert ctrl.accept[0] == 1.0
        # fitted costs survive a tenant change — prices don't reset
        assert ctrl.sec_per_token is not None
        assert ctrl.row_cost is not None

    def test_static_policy_ignores_everything(self):
        ctrl = _calibrated(SpecController(1, draft=3, branches=2,
                                          adaptive=False))
        for _ in range(12):
            ctrl.note_result(0, 3, 0)
        assert ctrl.plan(0) == (3, 2)          # never adapts

    def test_state_snapshot_shape(self):
        ctrl = _calibrated(SpecController(2, draft=4, branches=2))
        ctrl.note_result(0, 4, 2)
        s = ctrl.state()
        assert s["drafted"] == 4 and s["accepted"] == 2
        assert 0.0 <= s["accept_rate"] <= 1.0
        assert len(s["slots"]) == 2
        assert set(s["slots"][0]) == {"accept_ewma", "disabled"}


# --------------------------------------------------- engine integration
from gofr_tpu.serving.engine import EngineConfig, SamplingParams
from gofr_tpu.serving.glue import demo_llama_engine

PATTERN = [11, 22, 33, 44] * 12


def test_engine_rejects_oversized_tree_config():
    with pytest.raises(ValueError, match="bitmask"):
        demo_llama_engine(EngineConfig(speculative=True, spec_draft=8,
                                       spec_branches=4))


def test_ngram_index_rebuilds_after_preempt_fold():
    """Preemption folds generated tokens into the prompt — the
    incremental index must detect the rewritten stream and rebuild,
    not extend a stale view of it."""
    cfg = EngineConfig(max_batch=2, max_seq=128, seed=9,
                       kv_layout="paged", page_size=16,
                       prefill_buckets=(64,), speculative=True)
    engine = demo_llama_engine(cfg)
    req = engine.submit(PATTERN[:24], SamplingParams(
        temperature=0.0, max_new_tokens=32))
    engine._admit_batch([engine.waiting.pop_batch(1)[0]])
    engine._collect_prefills()
    assert len(req.generated) == 1
    engine._draft_proposals(req)
    idx = req.spec_index
    assert idx is not None
    assert idx.prompt_len == 24
    assert idx.size == 24 + len(req.generated)
    prompt_before = len(req.prompt_tokens)
    engine._preempt(req.slot)
    assert len(req.prompt_tokens) > prompt_before  # generated folded in
    # re-admit the requeued continuation and draft again
    batch, engine._requeued = engine._requeued, []
    engine._requeued_set.clear()
    engine._admit_batch(batch)
    engine._collect_prefills()
    engine._draft_proposals(req)
    idx2 = req.spec_index
    assert idx2 is not idx                     # rebuilt, not extended
    assert idx2.prompt_len == len(req.prompt_tokens)
    engine._shutdown_cleanup("test over")


def test_controller_slot_state_resets_per_tenant_and_restart():
    """_reset_runtime_state (shared by stop/start and the crash
    supervisor) voids slot ownership so a re-admitted slot re-seeds
    its EWMA; fitted prices and lifetime totals survive."""
    cfg = EngineConfig(max_batch=2, max_seq=128, seed=9,
                       prefill_buckets=(64,), speculative=True)
    engine = demo_llama_engine(cfg)
    ctrl = engine._spec_ctrl
    ctrl.note_decode(0.01, 10)
    ctrl.note_verify(0.001, 2, 5)
    ctrl.note_result(0, 4, 0)
    ctrl.accept[0] = 0.0
    ctrl.disabled[0] = True
    engine._spec_ctrl_owner[0] = object()      # pretend slot 0 is owned
    engine._reset_runtime_state()
    assert engine._spec_ctrl_owner == [None, None]
    # the controller object survives with its fitted costs + ledger
    assert ctrl.sec_per_token is not None
    assert ctrl.drafted_total == 4
    # next tenant in slot 0 resets the slot EWMA through the owner
    # check in _draft_proposals
    req = engine.submit(PATTERN, SamplingParams(
        temperature=0.0, max_new_tokens=8))
    engine._admit_batch([engine.waiting.pop_batch(1)[0]])
    engine._collect_prefills()
    engine._draft_proposals(req)
    assert not ctrl.disabled[req.slot]
    assert ctrl.accept[req.slot] == 1.0
    engine._shutdown_cleanup("test over")


def test_adaptive_controller_preserves_greedy_identity():
    """The controller only decides WHETHER to draft — greedy outputs
    stay identical to vanilla decode with adaptation on, off, and
    with multi-branch trees."""
    import time as _t

    def run(engine, n=20):
        engine.start()
        try:
            req = engine.submit_sync(PATTERN, SamplingParams(
                temperature=0.0, max_new_tokens=n))
            assert req.error is None, req.error
            return list(req.generated), dict(engine.stats)
        finally:
            engine.stop()

    base = dict(max_batch=2, max_seq=256, prefill_buckets=(64,), seed=9)
    vanilla, _ = run(demo_llama_engine(EngineConfig(**base)))
    for extra in (dict(spec_adaptive=True, spec_branches=2),
                  dict(spec_adaptive=False, spec_branches=1),
                  dict(spec_adaptive=False, spec_branches=4,
                       spec_draft=3)):
        engine = demo_llama_engine(EngineConfig(speculative=True,
                                                **base, **extra))
        got, stats = run(engine)
        assert got == vanilla, extra
        assert stats["spec_passes"] > 0
        state = engine.efficiency_state()["spec"]
        assert state["drafted"] >= state["accepted"] >= 0


def test_disabled_slots_fall_back_to_plain_decode():
    """A workload the drafter can hit n-grams on but the model never
    confirms: the controller must disable the slot and the engine
    must keep decoding plainly (correct tokens, no stall)."""
    cfg = EngineConfig(max_batch=1, max_seq=256, prefill_buckets=(64,),
                      seed=9, speculative=True, spec_accept_floor=0.9,
                      spec_probe_interval=100)
    engine = demo_llama_engine(cfg)
    base = EngineConfig(max_batch=1, max_seq=256,
                        prefill_buckets=(64,), seed=9)
    vanilla_engine = demo_llama_engine(base)

    def run(e):
        e.start()
        try:
            req = e.submit_sync(PATTERN, SamplingParams(
                temperature=0.0, max_new_tokens=24))
            assert req.error is None, req.error
            return list(req.generated)
        finally:
            e.stop()

    assert run(engine) == run(vanilla_engine)
