"""Serving-path observability: engine tracing, flight recorder, the
full Prometheus engine surface, and the profiler-capture endpoints.

The hard invariant under test: observability fully enabled (tracer +
flight recorder + metrics) adds ZERO host->device transfers to the
steady-state decode path and does not change a single generated token.
Everything is assembled host-side from timestamps the engine already
collects (serving/observability.py).
"""

import json
import re
import time
from pathlib import Path

import jax
import pytest

from gofr_tpu.container.container import Container
from gofr_tpu.metrics.registry import Manager as MetricsManager
from gofr_tpu.serving.engine import EngineConfig, SamplingParams
from gofr_tpu.serving.glue import demo_llama_engine
from gofr_tpu.serving.observability import FlightRecorder, ProfilerCapture
from gofr_tpu.serving.tokenizer import ByteTokenizer
from gofr_tpu.tracing.tracer import InMemoryExporter, Tracer

from .apputil import AppRunner

SERVING_DIR = Path(__file__).resolve().parent.parent / "gofr_tpu" / "serving"

# first string-literal argument of any metrics write call
_WRITE_RE = re.compile(
    r"(?:record_histogram|set_gauge|increment_counter|add_counter|"
    r"delta_up_down_counter)\(\s*['\"]([A-Za-z0-9_]+)['\"]")


def _run(eng, prompts, n, *, tracer=None, timeout=120):
    eng.start()
    sp = SamplingParams(temperature=0.0, max_new_tokens=n)
    if tracer is not None:
        with tracer.start_span("parent"):
            reqs = [eng.submit(p, sp) for p in prompts]
    else:
        reqs = [eng.submit(p, sp) for p in prompts]
    deadline = time.time() + timeout
    while time.time() < deadline and any(
            r.finished_at is None and r.error is None for r in reqs):
        time.sleep(0.005)
    eng.stop()
    assert all(r.error is None for r in reqs), [r.error for r in reqs]
    return reqs


# ------------------------------------------------------ registry coverage
def test_every_serving_metric_write_is_registered():
    """Every metric name written anywhere under gofr_tpu/serving/ must
    be registered by attach_metrics or the container's framework set —
    an unregistered write is a silent log-and-drop."""
    written = set()
    for path in SERVING_DIR.glob("*.py"):
        written.update(_WRITE_RE.findall(path.read_text()))
    assert written, "no metric writes found — the scan regex broke"

    container = Container()
    container.register_framework_metrics()
    # tenant metering + SLO + fleet/router + event-ledger series must
    # live in the CONTAINER framework set (not only attach_metrics):
    # federation merges them across hosts and leaders/aggregators
    # never call attach_metrics
    framework_missing = sorted(
        n for n in written
        if n.startswith(("app_tenant_", "app_slo_", "app_fleet_",
                         "app_router_", "app_events_"))
        and container.metrics.get(n) is None)
    assert not framework_missing, (
        f"tenant/SLO metric(s) written in serving/ but absent from the "
        f"container framework set: {framework_missing}")
    eng = demo_llama_engine(EngineConfig(max_batch=2, max_seq=64))
    eng.attach_metrics(container.metrics)
    missing = sorted(n for n in written
                     if container.metrics.get(n) is None)
    assert not missing, (
        f"metric(s) written in serving/ but never registered: {missing}")


def test_render_federated_merges_tenant_counters_across_hosts():
    """The per-tenant counters ride the PR 4 federation path: identical
    tenant labelsets SUM across hosts in merge_snapshots, and the
    federated exposition carries each host's series under its host
    label."""
    from gofr_tpu.metrics.registry import merge_snapshots, render_federated
    managers = {}
    for host, tokens in (("host-a", 10), ("host-b", 32)):
        m = MetricsManager()
        m.new_counter("app_tenant_completion_tokens",
                      "generated tokens by tenant")
        m.add_counter("app_tenant_completion_tokens", float(tokens),
                      tenant="acme")
        managers[host] = m
    snaps = {h: m.snapshot() for h, m in managers.items()}
    merged = merge_snapshots(snaps)
    fam = merged["metrics"]["app_tenant_completion_tokens"]
    series = {tuple(sorted(s["labels"].items())): s["value"]
              for s in fam["series"]}
    assert series[(("tenant", "acme"),)] == 42.0  # summed, one labelset
    text = render_federated(snaps)
    assert 'app_tenant_completion_tokens{host="host-a",tenant="acme"} 10' \
        in text
    assert 'app_tenant_completion_tokens{host="host-b",tenant="acme"} 32' \
        in text


def test_attach_metrics_registers_on_bare_manager():
    """An engine attached to a fresh Manager (no container) registers
    its full surface itself — serve_model-less embedding works."""
    m = MetricsManager()
    eng = demo_llama_engine(EngineConfig(max_batch=2, max_seq=64))
    eng.attach_metrics(m)
    for name in ("app_engine_batch_occupancy", "app_chat_queue_seconds",
                 "app_chat_tpot_seconds", "app_chat_e2e_seconds",
                 "app_engine_kv_pool_utilization", "app_engine_mfu",
                 "app_engine_preemptions", "app_engine_spec_drafted"):
        assert m.get(name) is not None, name


# -------------------------------------------- zero-perturbation invariant
def test_steady_state_zero_h2d_with_observability_enabled():
    """The transfer-guard contract of test_decode_state, with tracing +
    flight recorder + metrics ALL on: steady-state decode still uploads
    nothing."""
    container = Container()
    container.register_framework_metrics()
    tracer = Tracer(exporter=InMemoryExporter())
    eng = demo_llama_engine(EngineConfig(max_batch=4, max_seq=256,
                                         seed=0), tracer=tracer)
    eng.attach_metrics(container.metrics)
    params = SamplingParams(temperature=0.0, max_new_tokens=200)
    with tracer.start_span("parent"):
        reqs = [eng.submit([1 + i, 2, 3], params) for i in range(3)]
    batch = eng.waiting.pop_batch(len(reqs), first_wait_s=0.5)
    assert batch and len(batch) == len(reqs)
    eng._admit_batch(batch)
    eng._collect_prefills()
    # two unguarded passes: admission upload, then the use_prev flip
    for _ in range(2):
        eng._decode_step()
        eng._drain_pending()
    transfers = eng.stats["h2d_transfers"]
    with jax.transfer_guard_host_to_device("disallow"):
        for _ in range(3):
            eng._decode_step()
            eng._drain_pending()
    assert eng.stats["h2d_transfers"] == transfers
    # ...and the observability layer actually observed those passes
    kinds = [p["kind"] for p in eng.recorder.snapshot()["passes"]]
    assert kinds.count("decode") >= 5
    assert container.metrics.get_histogram_count(
        "app_engine_batch_occupancy") >= 5
    last = eng.recorder.snapshot()["passes"][-1]
    assert last["h2d"] == 0 and last["occupancy"] == 3
    assert last["tokens"] > 0


@pytest.mark.parametrize("layout_kw", [
    {},
    {"kv_layout": "paged", "page_size": 16, "paged_attention": "view"},
])
def test_greedy_bit_identical_with_observability_enabled(layout_kw):
    """Greedy token streams with tracer+recorder+metrics enabled are
    bit-identical to the bare engine (both KV layouts)."""
    prompts = [[5 + i, 2, 9] for i in range(3)]

    def cfg():
        return EngineConfig(max_batch=4, max_seq=128, seed=11,
                            **layout_kw)

    bare = demo_llama_engine(cfg())
    want = [r.generated for r in _run(bare, prompts, 24)]

    container = Container()
    container.register_framework_metrics()
    tracer = Tracer(exporter=InMemoryExporter())
    obs = demo_llama_engine(cfg(), tracer=tracer)
    obs.attach_metrics(container.metrics)
    got_reqs = _run(obs, prompts, 24, tracer=tracer)
    assert [r.generated for r in got_reqs] == want
    # the observed run produced spans for every request
    names = [s.name for s in tracer.exporter.spans]
    assert names.count("engine.request") == len(prompts)


# --------------------------------------------------------- flight recorder
def test_flight_recorder_ring_and_request_logs():
    rec = FlightRecorder(size=4, request_logs=2)
    for i in range(10):
        rec.record_pass("decode", tokens=i)
    snap = rec.snapshot()
    assert len(snap["passes"]) == 4                    # ring bounded
    assert [p["tokens"] for p in snap["passes"]] == [6, 7, 8, 9]
    assert snap["passes_recorded"] == 10
    assert rec.snapshot(2)["passes"][-1]["seq"] == 10  # last-N works
    assert rec.summary()["by_kind"] == {"decode": 10}
    disabled = FlightRecorder(size=0)
    disabled.record_pass("decode")
    assert disabled.snapshot()["passes"] == []


def test_engine_health_and_crash_dump_carry_flight_summary():
    eng = demo_llama_engine(EngineConfig(max_batch=2, max_seq=64, seed=3))

    class SpyLogger:
        lines: list = []

        def error(self, msg, **kw):
            self.lines.append(str(msg))

        def warn(self, msg, **kw):
            pass

        def info(self, msg, **kw):
            pass

    eng.logger = SpyLogger()
    _run(eng, [[1, 2, 3]], 6)
    health = eng.health_check()
    assert health["flight"]["passes_recorded"] >= 1
    eng._crash(RuntimeError("boom"))
    assert any("flight recorder" in ln for ln in SpyLogger.lines)
    assert eng.health_check()["status"] == "DOWN"


def test_spec_verify_recorded_in_ring_and_counters():
    m = MetricsManager()
    eng = demo_llama_engine(EngineConfig(
        max_batch=2, max_seq=256, seed=5, speculative=True,
        spec_ngram=1, decode_steps_per_pass=2))
    eng.attach_metrics(m)
    pattern = [7, 11, 13, 7, 11, 13, 7, 11]
    _run(eng, [pattern], 24)
    assert eng.stats["spec_passes"] > 0
    kinds = {p["kind"] for p in eng.recorder.snapshot()["passes"]}
    assert "spec_verify" in kinds
    assert m.get("app_engine_spec_drafted").get() > 0
    assert m.get("app_engine_spec_accepted").get() >= 0


# -------------------------------------------------------------- profiler
def test_profiler_capture_single_flight(tmp_path):
    cap = ProfilerCapture(base_dir=str(tmp_path))
    out = cap.start()
    assert out["ok"], out
    again = cap.start()
    assert not again["ok"] and "already" in again["error"]
    assert cap.status()["running"]
    stopped = cap.stop()
    assert stopped["ok"] and stopped["dir"] == out["dir"]
    assert not cap.status()["running"]
    assert not cap.stop()["ok"]  # idempotent-safe


# ------------------------------------------------------------------- e2e
@pytest.fixture(scope="module")
def obs_app():
    engine = demo_llama_engine(EngineConfig(
        max_batch=4, max_seq=128, seed=0, kv_layout="paged",
        page_size=16, prefix_cache=True, paged_attention="view"))

    def build(app):
        app.serve_model("llm", engine, ByteTokenizer())

    runner = AppRunner(build=build,
                       config={"TRACE_EXPORTER": "memory",
                               "PROFILER_ENABLED": "true"})
    with runner as app:
        yield app


def test_e2e_traceparent_links_engine_spans(obs_app):
    """A chat request with a W3C traceparent produces linked engine.*
    child spans in the in-memory exporter: HTTP span -> engine.request
    -> queue/prefill/decode/retire, one trace end to end."""
    trace_id = "ab" * 16
    status, _, data = obs_app.request(
        "POST", "/chat",
        {"prompt": "trace me end to end", "max_tokens": 8,
         "temperature": 0.0},
        headers={"traceparent": f"00-{trace_id}-{'cd' * 8}-01"})
    assert status == 201
    body = json.loads(data)["data"]
    assert body["usage"]["tpot_ms"] is not None
    spans = obs_app.app.container.tracer.exporter.spans
    mine = [s for s in spans if s.trace_id == trace_id]
    http_span = next(s for s in mine if s.name == "POST /chat")
    assert http_span.parent_id == "cd" * 8
    by_name = {s.name: s for s in mine}
    root = by_name["engine.request"]
    assert root.parent_id == http_span.span_id
    for name in ("engine.queue", "engine.prefill", "engine.decode",
                 "engine.retire"):
        assert by_name[name].parent_id == root.span_id, name
    assert by_name["engine.decode"].attributes["tokens"] == 8
    assert by_name["engine.queue"].end_time >= by_name[
        "engine.queue"].start_time


def test_e2e_debug_engine_returns_pass_records(obs_app):
    status, body = obs_app.get_json("/debug/engine?n=8")
    assert status == 200
    llm = body["data"]["llm"]
    assert llm["health"]["status"] == "UP"
    assert llm["flight"]["passes"], "no pass records served"
    assert len(llm["flight"]["passes"]) <= 8
    last = llm["flight"]["passes"][-1]
    assert {"seq", "kind", "t"} <= set(last)


def test_e2e_metrics_expose_engine_surface(obs_app):
    # a second request makes sure samples exist regardless of ordering,
    # then give the throttled gauges one refresh window
    status, _, _ = obs_app.request(
        "POST", "/chat", {"prompt": "trace me end to end",
                          "max_tokens": 8, "temperature": 0.0})
    assert status == 201
    time.sleep(0.6)
    _, _, data = obs_app.request("GET", "/metrics",
                                 port=obs_app.metrics_port)
    text = data.decode()
    series = {}
    for line in text.splitlines():
        if line.startswith("#") or not line.strip():
            continue
        name_part, _, value = line.rpartition(" ")
        series[name_part.split("{", 1)[0]] = float(value)
    for name in ("app_chat_queue_seconds_count",
                 "app_chat_tpot_seconds_count",
                 "app_chat_e2e_seconds_count",
                 "app_engine_batch_occupancy_count",
                 "app_engine_kv_pool_utilization"):
        assert series.get(name, 0.0) > 0.0, (name, series.get(name))
    # present even when zero-valued on CPU
    for name in ("app_engine_mfu", "app_engine_tokens_per_second",
                 "app_engine_kv_pool_fragmentation",
                 "app_engine_prefix_cache_pages"):
        assert name in series, name


def test_e2e_debug_efficiency_conserves(obs_app):
    """GET /debug/efficiency serves the goodput classification with
    the conservation invariant intact, watermarks with timestamps,
    and the recompile-sentinel state."""
    status, _, _ = obs_app.request(
        "POST", "/chat", {"prompt": "efficiency probe",
                          "max_tokens": 8, "temperature": 0.0})
    assert status == 201
    status, body = obs_app.get_json("/debug/efficiency")
    assert status == 200
    eff = body["data"]["llm"]
    gp = eff["goodput"]
    assert gp["busy_s"] > 0
    total = gp["useful_s"] + sum(gp["waste_s"].values())
    # each JSON field is rounded to 6 decimals, so the serialized sum
    # may be off by a few ulps of the rounding grain; the raw-float
    # invariant is exact (conservation_error_s, and test_goodput.py)
    assert abs(total - gp["busy_s"]) < 5e-6, gp
    assert abs(gp["conservation_error_s"]) < 1e-9, gp
    assert 0.0 < gp["goodput_ratio"] <= 1.0
    assert set(gp["waste_s"]) == {"padding", "preempt_recompute",
                                 "spec_rejected", "bubble",
                                 "integrity_probe"}
    assert eff["watermarks"]["kv_pages"]["value"] > 0
    assert "t" in eff["watermarks"]["kv_pages"]
    assert "recompiles" in eff["recompiles"]


def test_e2e_debug_engine_exposes_trace_drops(obs_app):
    """The bounded span exporter's eviction counter is surfaced in
    /debug/engine — a truncated trace capture must say so."""
    status, body = obs_app.get_json("/debug/engine?n=1")
    assert status == 200
    traces = body["data"]["traces"]
    assert traces["dropped_spans"] == 0
    assert traces["buffered_spans"] >= 1
    assert traces["max_spans"] == 8192
    # scrape refreshes the gauge from the exporter
    _, _, data = obs_app.request("GET", "/metrics",
                                 port=obs_app.metrics_port)
    assert "app_traces_dropped_spans 0" in data.decode()


def test_e2e_profiler_endpoints(obs_app, tmp_path_factory):
    target = str(tmp_path_factory.mktemp("xprof"))
    status, _, data = obs_app.request("POST", "/debug/profile/start",
                                      {"dir": target})
    assert status in (200, 201)
    out = json.loads(data)["data"]
    assert out["ok"], out
    # double-start is refused, not crashed
    status, _, data = obs_app.request("POST", "/debug/profile/start", {})
    assert not json.loads(data)["data"]["ok"]
    status, _, data = obs_app.request("POST", "/debug/profile/stop", {})
    stopped = json.loads(data)["data"]
    assert stopped["ok"] and stopped["dir"] == target
