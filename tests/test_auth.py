"""Auth middleware: basic, API-key, OAuth JWT/JWKS — over a real server."""

from __future__ import annotations

import base64
import json
import time

import pytest

from gofr_tpu.http.auth import (
    JWTError,
    OAuthProvider,
    jwk_to_public_key,
    jwt_sign_hs256,
    jwt_verify,
)

from .apputil import AppRunner


def _basic(user: str, password: str) -> dict:
    token = base64.b64encode(f"{user}:{password}".encode()).decode()
    return {"Authorization": f"Basic {token}"}


def whoami(ctx):
    return ctx.auth_info


class TestBasicAuth:
    def _runner(self) -> AppRunner:
        def build(app):
            app.enable_basic_auth(alice="secret", bob="hunter2")
            app.get("/whoami", whoami)
        return AppRunner(build=build)

    def test_valid_credentials(self):
        with self._runner() as r:
            status, body = r.get_json("/whoami", headers=_basic("alice", "secret"))
            assert status == 200
            assert body["data"]["username"] == "alice"

    def test_wrong_password_and_missing_header(self):
        with self._runner() as r:
            status, _, _ = r.request("GET", "/whoami",
                                     headers=_basic("alice", "nope"))
            assert status == 401
            status, headers, _ = r.request("GET", "/whoami")
            assert status == 401
            assert headers.get("WWW-Authenticate") == "Basic"

    def test_well_known_exempt(self):
        with self._runner() as r:
            status, _ = r.get_json("/.well-known/alive")
            assert status == 200

    def test_non_ascii_credentials_reject_cleanly(self):
        with self._runner() as r:
            status, _, _ = r.request("GET", "/whoami",
                                     headers=_basic("alice", "pässwörd"))
            assert status == 401  # not 500

    def test_validator_form(self):
        def build(app):
            app.enable_basic_auth_with_validator(
                lambda u, p: u == "svc" and p == "tok")
            app.get("/whoami", whoami)
        with AppRunner(build=build) as r:
            status, body = r.get_json("/whoami", headers=_basic("svc", "tok"))
            assert status == 200 and body["data"]["username"] == "svc"
            status, _, _ = r.request("GET", "/whoami", headers=_basic("svc", "x"))
            assert status == 401


class TestAPIKeyAuth:
    def test_static_keys(self):
        from gofr_tpu.http.auth import credential_fingerprint

        def build(app):
            app.enable_api_key_auth("k1", "k2")
            app.get("/whoami", whoami)
        with AppRunner(build=build) as r:
            status, body = r.get_json("/whoami", headers={"X-Api-Key": "k2"})
            # the principal carries the key's fingerprint, never the
            # raw credential — nothing downstream can leak it
            assert status == 200
            assert body["data"]["api_key"] == credential_fingerprint("k2")
            assert "k2" not in json.dumps(body["data"])
            status, _, _ = r.request("GET", "/whoami",
                                     headers={"X-Api-Key": "bad"})
            assert status == 401
            status, _, _ = r.request("GET", "/whoami")
            assert status == 401

    def test_key_names_map_to_tenant(self):
        def build(app):
            app.enable_api_key_auth("bare",
                                    key_names={"named": "team-x"})
            app.get("/whoami", whoami)
        with AppRunner(build=build) as r:
            status, body = r.get_json("/whoami",
                                      headers={"X-Api-Key": "named"})
            assert status == 200
            assert body["data"]["tenant"] == "team-x"
            status, body = r.get_json("/whoami",
                                      headers={"X-Api-Key": "bare"})
            assert status == 200 and "tenant" not in body["data"]

    def test_validator(self):
        def build(app):
            app.enable_api_key_auth_with_validator(
                lambda k: k.startswith("team-"))
            app.get("/whoami", whoami)
        with AppRunner(build=build) as r:
            status, _ = r.get_json("/whoami", headers={"X-Api-Key": "team-a"})
            assert status == 200


class TestJWT:
    SECRET = "sekrit"

    def test_hs256_roundtrip(self):
        token = jwt_sign_hs256({"sub": "u1", "exp": time.time() + 60},
                               self.SECRET)
        claims = jwt_verify(token, {"": self.SECRET})
        assert claims["sub"] == "u1"

    def test_expired(self):
        token = jwt_sign_hs256({"sub": "u1", "exp": time.time() - 120},
                               self.SECRET)
        with pytest.raises(JWTError, match="expired"):
            jwt_verify(token, {"": self.SECRET})

    def test_bad_signature(self):
        token = jwt_sign_hs256({"sub": "u1"}, self.SECRET)
        with pytest.raises(JWTError, match="signature"):
            jwt_verify(token, {"": "other-secret"})

    def test_audience_issuer(self):
        token = jwt_sign_hs256({"aud": "api", "iss": "me"}, self.SECRET)
        jwt_verify(token, {"": self.SECRET}, audience="api", issuer="me")
        with pytest.raises(JWTError, match="audience"):
            jwt_verify(token, {"": self.SECRET}, audience="other")
        with pytest.raises(JWTError, match="issuer"):
            jwt_verify(token, {"": self.SECRET}, issuer="them")

    def test_rs256_via_jwk(self):
        from cryptography.hazmat.primitives.asymmetric import padding, rsa
        from cryptography.hazmat.primitives import hashes
        private = rsa.generate_private_key(public_exponent=65537, key_size=2048)
        numbers = private.public_key().public_numbers()

        def b64url_int(n: int) -> str:
            raw = n.to_bytes((n.bit_length() + 7) // 8, "big")
            return base64.urlsafe_b64encode(raw).rstrip(b"=").decode()

        jwk = {"kty": "RSA", "kid": "k1",
               "n": b64url_int(numbers.n), "e": b64url_int(numbers.e)}

        def enc(obj) -> str:
            return base64.urlsafe_b64encode(
                json.dumps(obj).encode()).rstrip(b"=").decode()

        signing_input = (enc({"alg": "RS256", "kid": "k1"}) + "."
                         + enc({"sub": "rsa-user"}))
        sig = private.sign(signing_input.encode(), padding.PKCS1v15(),
                           hashes.SHA256())
        token = (signing_input + "."
                 + base64.urlsafe_b64encode(sig).rstrip(b"=").decode())

        key = jwk_to_public_key(jwk)
        claims = jwt_verify(token, {"k1": key})
        assert claims["sub"] == "rsa-user"

        provider = OAuthProvider(jwks={"keys": [jwk]})

        class FakeReq:
            path = "/x"
            def header(self, k):
                return f"Bearer {token}" if k == "authorization" else ""
        info = provider.authenticate(FakeReq())
        assert info["claims"]["sub"] == "rsa-user"


class TestJWKSRefresh:
    def test_fetch_failure_backs_off(self):
        provider = OAuthProvider("http://127.0.0.1:1/jwks",
                                 refresh_interval=300.0)
        t0 = time.time()
        provider._refresh_if_stale()  # inline fetch fails fast (conn refused)
        assert provider._keys == {}
        # clock advanced => next attempt only after FAILURE_BACKOFF
        assert provider._fetched_at > t0 - 300.0 + 25.0

    def test_refresh_serves_stale_keys_without_blocking(self):
        provider = OAuthProvider("http://127.0.0.1:1/jwks",
                                 keys={"": "sekrit"}, refresh_interval=0.0)
        token = jwt_sign_hs256({"sub": "x"}, "sekrit")

        class FakeReq:
            path = "/x"
            def header(self, k):
                return f"Bearer {token}" if k == "authorization" else ""
        t0 = time.time()
        info = provider.authenticate(FakeReq())
        assert info["claims"]["sub"] == "x"
        assert time.time() - t0 < 1.0  # background refresh, no 5s stall


class TestOAuthEndToEnd:
    def test_bearer_over_server(self):
        secret = "svc-secret"

        def build(app):
            from gofr_tpu.http.auth import OAuthProvider, auth_middleware
            app._middlewares.append(auth_middleware(
                OAuthProvider(keys={"": secret}, audience="api"),
                scheme="Bearer"))
            app.get("/claims", lambda ctx: ctx.auth_info["claims"])

        token = jwt_sign_hs256({"sub": "u9", "aud": "api"}, secret)
        with AppRunner(build=build) as r:
            status, body = r.get_json(
                "/claims", headers={"Authorization": f"Bearer {token}"})
            assert status == 200 and body["data"]["sub"] == "u9"
            status, _, _ = r.request(
                "GET", "/claims", headers={"Authorization": "Bearer junk"})
            assert status == 401


class TestAuthEdgeCases:
    """Hostile-input edges: malformed headers, algorithm confusion,
    unknown kids — every one must be a clean 401/None, never a crash."""

    def test_basic_header_malformed_variants(self):
        from gofr_tpu.http.auth import BasicAuthProvider

        provider = BasicAuthProvider(users={"u": "p"})

        class Req:
            def __init__(self, header):
                self._h = header
                self.path = "/x"

            def header(self, k):
                return self._h if k == "authorization" else ""

        assert provider.authenticate(Req("")) is None
        assert provider.authenticate(Req("Basic")) is None
        assert provider.authenticate(Req("Basic !!!notbase64!!!")) is None
        # valid base64 but no colon inside
        nocolon = base64.b64encode(b"justauser").decode()
        assert provider.authenticate(Req(f"Basic {nocolon}")) is None
        # Bearer scheme sent to a Basic provider
        assert provider.authenticate(Req("Bearer abc")) is None

    def test_hs256_token_against_rsa_keys_is_rejected_not_crash(self):
        """Algorithm-confusion: alg=HS256 with an RSA verification key
        must raise JWTError (and authenticate -> None), not
        AttributeError."""
        from cryptography.hazmat.primitives.asymmetric import rsa

        public = rsa.generate_private_key(
            public_exponent=65537, key_size=2048).public_key()
        token = jwt_sign_hs256({"sub": "evil"}, "whatever",
                               headers={"kid": "k1"})
        with pytest.raises(JWTError, match="not a secret"):
            jwt_verify(token, {"k1": public})

        provider = OAuthProvider(keys={"k1": public})

        class Req:
            path = "/x"

            def header(self, k):
                return f"Bearer {token}" if k == "authorization" else ""

        assert provider.authenticate(Req()) is None  # no exception

    def test_rs256_token_against_shared_secret_is_rejected(self):
        token = jwt_sign_hs256({"sub": "x"}, "s")
        # forge the alg field to RS256 with the same payload
        header = base64.urlsafe_b64encode(
            json.dumps({"alg": "RS256"}).encode()).rstrip(b"=").decode()
        body = token.split(".")[1]
        forged = f"{header}.{body}.{token.split('.')[2]}"
        with pytest.raises(JWTError, match="not an RSA"):
            jwt_verify(forged, {"": "s"})

    def test_alg_none_is_rejected(self):
        def enc(obj) -> str:
            return base64.urlsafe_b64encode(
                json.dumps(obj).encode()).rstrip(b"=").decode()

        token = f"{enc({'alg': 'none'})}.{enc({'sub': 'evil'})}."
        with pytest.raises(JWTError, match="unsupported alg"):
            jwt_verify(token, {"": "s"})

    def test_unknown_kid_with_multiple_keys(self):
        token = jwt_sign_hs256({"sub": "x"}, "right",
                               headers={"kid": "nope"})
        with pytest.raises(JWTError, match="no key"):
            jwt_verify(token, {"a": "right", "b": "other"})

    def test_garbage_tokens(self):
        for bad in ("two.parts", "a.b.c.d", "", "....",
                    "!!!.@@@.###"):
            with pytest.raises(JWTError):
                jwt_verify(bad, {"": "s"})

    def test_oauth_provider_survives_garbage_bearer_over_server(self):
        def build(app):
            from gofr_tpu.http.auth import OAuthProvider, auth_middleware
            app._middlewares.append(auth_middleware(
                OAuthProvider(keys={"": "sek"}), scheme="Bearer"))
            app.get("/p", lambda ctx: "ok")

        with AppRunner(build=build) as r:
            for header in ({"Authorization": "Bearer not.a.jwt"},
                           {"Authorization": "Bearer "},
                           {"Authorization": "Negotiate blah"},
                           {}):
                status, _ = r.get_json("/p", headers=header)
                assert status == 401
            good = jwt_sign_hs256({"sub": "x"}, "sek")
            status, _ = r.get_json(
                "/p", headers={"Authorization": f"Bearer {good}"})
            assert status == 200

    def test_api_key_empty_and_wrong(self):
        def build(app):
            app.enable_api_key_auth("key-1")
            app.get("/p", lambda ctx: "ok")

        with AppRunner(build=build) as r:
            assert r.get_json("/p")[0] == 401
            assert r.get_json("/p", headers={"X-Api-Key": ""})[0] == 401
            assert r.get_json("/p", headers={"X-Api-Key": "nope"})[0] == 401
            assert r.get_json("/p", headers={"X-Api-Key": "key-1"})[0] == 200
