"""Auth middleware: basic, API-key, OAuth JWT/JWKS — over a real server."""

from __future__ import annotations

import base64
import json
import time

import pytest

from gofr_tpu.http.auth import (
    JWTError,
    OAuthProvider,
    jwk_to_public_key,
    jwt_sign_hs256,
    jwt_verify,
)

from .apputil import AppRunner


def _basic(user: str, password: str) -> dict:
    token = base64.b64encode(f"{user}:{password}".encode()).decode()
    return {"Authorization": f"Basic {token}"}


def whoami(ctx):
    return ctx.auth_info


class TestBasicAuth:
    def _runner(self) -> AppRunner:
        def build(app):
            app.enable_basic_auth(alice="secret", bob="hunter2")
            app.get("/whoami", whoami)
        return AppRunner(build=build)

    def test_valid_credentials(self):
        with self._runner() as r:
            status, body = r.get_json("/whoami", headers=_basic("alice", "secret"))
            assert status == 200
            assert body["data"]["username"] == "alice"

    def test_wrong_password_and_missing_header(self):
        with self._runner() as r:
            status, _, _ = r.request("GET", "/whoami",
                                     headers=_basic("alice", "nope"))
            assert status == 401
            status, headers, _ = r.request("GET", "/whoami")
            assert status == 401
            assert headers.get("WWW-Authenticate") == "Basic"

    def test_well_known_exempt(self):
        with self._runner() as r:
            status, _ = r.get_json("/.well-known/alive")
            assert status == 200

    def test_non_ascii_credentials_reject_cleanly(self):
        with self._runner() as r:
            status, _, _ = r.request("GET", "/whoami",
                                     headers=_basic("alice", "pässwörd"))
            assert status == 401  # not 500

    def test_validator_form(self):
        def build(app):
            app.enable_basic_auth_with_validator(
                lambda u, p: u == "svc" and p == "tok")
            app.get("/whoami", whoami)
        with AppRunner(build=build) as r:
            status, body = r.get_json("/whoami", headers=_basic("svc", "tok"))
            assert status == 200 and body["data"]["username"] == "svc"
            status, _, _ = r.request("GET", "/whoami", headers=_basic("svc", "x"))
            assert status == 401


class TestAPIKeyAuth:
    def test_static_keys(self):
        def build(app):
            app.enable_api_key_auth("k1", "k2")
            app.get("/whoami", whoami)
        with AppRunner(build=build) as r:
            status, body = r.get_json("/whoami", headers={"X-Api-Key": "k2"})
            assert status == 200 and body["data"]["api_key"] == "k2"
            status, _, _ = r.request("GET", "/whoami",
                                     headers={"X-Api-Key": "bad"})
            assert status == 401
            status, _, _ = r.request("GET", "/whoami")
            assert status == 401

    def test_validator(self):
        def build(app):
            app.enable_api_key_auth_with_validator(
                lambda k: k.startswith("team-"))
            app.get("/whoami", whoami)
        with AppRunner(build=build) as r:
            status, _ = r.get_json("/whoami", headers={"X-Api-Key": "team-a"})
            assert status == 200


class TestJWT:
    SECRET = "sekrit"

    def test_hs256_roundtrip(self):
        token = jwt_sign_hs256({"sub": "u1", "exp": time.time() + 60},
                               self.SECRET)
        claims = jwt_verify(token, {"": self.SECRET})
        assert claims["sub"] == "u1"

    def test_expired(self):
        token = jwt_sign_hs256({"sub": "u1", "exp": time.time() - 120},
                               self.SECRET)
        with pytest.raises(JWTError, match="expired"):
            jwt_verify(token, {"": self.SECRET})

    def test_bad_signature(self):
        token = jwt_sign_hs256({"sub": "u1"}, self.SECRET)
        with pytest.raises(JWTError, match="signature"):
            jwt_verify(token, {"": "other-secret"})

    def test_audience_issuer(self):
        token = jwt_sign_hs256({"aud": "api", "iss": "me"}, self.SECRET)
        jwt_verify(token, {"": self.SECRET}, audience="api", issuer="me")
        with pytest.raises(JWTError, match="audience"):
            jwt_verify(token, {"": self.SECRET}, audience="other")
        with pytest.raises(JWTError, match="issuer"):
            jwt_verify(token, {"": self.SECRET}, issuer="them")

    def test_rs256_via_jwk(self):
        from cryptography.hazmat.primitives.asymmetric import padding, rsa
        from cryptography.hazmat.primitives import hashes
        private = rsa.generate_private_key(public_exponent=65537, key_size=2048)
        numbers = private.public_key().public_numbers()

        def b64url_int(n: int) -> str:
            raw = n.to_bytes((n.bit_length() + 7) // 8, "big")
            return base64.urlsafe_b64encode(raw).rstrip(b"=").decode()

        jwk = {"kty": "RSA", "kid": "k1",
               "n": b64url_int(numbers.n), "e": b64url_int(numbers.e)}

        def enc(obj) -> str:
            return base64.urlsafe_b64encode(
                json.dumps(obj).encode()).rstrip(b"=").decode()

        signing_input = (enc({"alg": "RS256", "kid": "k1"}) + "."
                         + enc({"sub": "rsa-user"}))
        sig = private.sign(signing_input.encode(), padding.PKCS1v15(),
                           hashes.SHA256())
        token = (signing_input + "."
                 + base64.urlsafe_b64encode(sig).rstrip(b"=").decode())

        key = jwk_to_public_key(jwk)
        claims = jwt_verify(token, {"k1": key})
        assert claims["sub"] == "rsa-user"

        provider = OAuthProvider(jwks={"keys": [jwk]})

        class FakeReq:
            path = "/x"
            def header(self, k):
                return f"Bearer {token}" if k == "authorization" else ""
        info = provider.authenticate(FakeReq())
        assert info["claims"]["sub"] == "rsa-user"


class TestJWKSRefresh:
    def test_fetch_failure_backs_off(self):
        provider = OAuthProvider("http://127.0.0.1:1/jwks",
                                 refresh_interval=300.0)
        t0 = time.time()
        provider._refresh_if_stale()  # inline fetch fails fast (conn refused)
        assert provider._keys == {}
        # clock advanced => next attempt only after FAILURE_BACKOFF
        assert provider._fetched_at > t0 - 300.0 + 25.0

    def test_refresh_serves_stale_keys_without_blocking(self):
        provider = OAuthProvider("http://127.0.0.1:1/jwks",
                                 keys={"": "sekrit"}, refresh_interval=0.0)
        token = jwt_sign_hs256({"sub": "x"}, "sekrit")

        class FakeReq:
            path = "/x"
            def header(self, k):
                return f"Bearer {token}" if k == "authorization" else ""
        t0 = time.time()
        info = provider.authenticate(FakeReq())
        assert info["claims"]["sub"] == "x"
        assert time.time() - t0 < 1.0  # background refresh, no 5s stall


class TestOAuthEndToEnd:
    def test_bearer_over_server(self):
        secret = "svc-secret"

        def build(app):
            from gofr_tpu.http.auth import OAuthProvider, auth_middleware
            app._middlewares.append(auth_middleware(
                OAuthProvider(keys={"": secret}, audience="api"),
                scheme="Bearer"))
            app.get("/claims", lambda ctx: ctx.auth_info["claims"])

        token = jwt_sign_hs256({"sub": "u9", "aud": "api"}, secret)
        with AppRunner(build=build) as r:
            status, body = r.get_json(
                "/claims", headers={"Authorization": f"Bearer {token}"})
            assert status == 200 and body["data"]["sub"] == "u9"
            status, _, _ = r.request(
                "GET", "/claims", headers={"Authorization": "Bearer junk"})
            assert status == 401
