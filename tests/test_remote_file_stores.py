"""Remote file stores: S3/GCS/Azure object adapters over the embedded
engine, and the FTP filesystem speaking real protocol bytes against
the in-process mini server."""

import io

import pytest

from gofr_tpu.container.container import Container
from gofr_tpu.datasource.file_store import FileError
from gofr_tpu.datasource.ftp import (FTPFileSystem, MiniFTPServer,
                                     SFTPFileSystem)
from gofr_tpu.datasource.object_store import (AzureBlobFileSystem,
                                              GCSFileSystem, ObjectNotFound,
                                              S3FileSystem)


# ------------------------------------------------------------ object family
@pytest.mark.parametrize("cls", [S3FileSystem, GCSFileSystem,
                                 AzureBlobFileSystem])
class TestObjectFileSystemSurface:
    def test_filesystem_roundtrip(self, cls):
        fs = cls("models")
        fs.connect()
        fs.create("weights/llama/params.npz", b"\x00\x01")
        assert fs.read("weights/llama/params.npz") == b"\x00\x01"
        fs.append("logs/run.txt", "a")
        fs.append("logs/run.txt", "b")
        assert fs.read_text("logs/run.txt") == "ab"
        assert fs.exists("logs/run.txt")
        info = fs.stat("logs/run.txt")
        assert (info.name, info.size, info.is_dir) == ("run.txt", 2, False)
        fs.rename("logs/run.txt", "logs/run2.txt")
        assert not fs.exists("logs/run.txt")
        fs.remove("logs/run2.txt")
        with pytest.raises(ObjectNotFound):
            fs.read("logs/run2.txt")

    def test_read_dir_emulates_one_level(self, cls):
        fs = cls("b")
        fs.create("a.txt", b"1")
        fs.create("sub/b.txt", b"2")
        fs.create("sub/deep/c.txt", b"3")
        entries = {e.name: e.is_dir for e in fs.read_dir()}
        assert entries == {"a.txt": False, "sub": True}
        sub = {e.name: e.is_dir for e in fs.read_dir("sub")}
        assert sub == {"b.txt": False, "deep": True}

    def test_rows_and_glob_and_health(self, cls):
        fs = cls("data")
        fs.create("t.csv", "x,y\n1,2\n3,4\n")
        rows = list(fs.read_rows("t.csv"))
        assert rows[0]["x"] == "1"
        fs.create("a/1.json", b"[]")
        assert fs.glob("a/*.json") == ["a/1.json"]
        assert fs.health_check()["status"] == "UP"


def test_s3_native_verbs():
    s3 = S3FileSystem("bkt")
    s3.put_object("k1", b"v1")
    s3.put_object("k2", b"v2")
    assert s3.get_object("k1") == b"v1"
    listing = s3.list_objects("k")
    assert [o["Key"] for o in listing] == ["k1", "k2"]
    s3.delete_object("k1")
    assert [o["Key"] for o in s3.list_objects()] == ["k2"]


def test_gcs_and_azure_native_verbs():
    gcs = GCSFileSystem("bkt")
    gcs.upload("blob1", b"x")
    assert gcs.download("blob1") == b"x"
    assert gcs.list_blobs() == ["blob1"]

    az = AzureBlobFileSystem("container")
    az.upload_blob("b1", b"y")
    with pytest.raises(FileError):
        az.upload_blob("b1", b"z", overwrite=False)
    assert az.download_blob("b1") == b"y"
    az.delete_blob("b1")
    assert az.list_blob_names() == []


def test_container_add_file_store_accepts_object_store():
    c = Container()
    fs = c.add_file_store(S3FileSystem("app-bucket"))
    assert fs.logger is c.logger
    assert c.health()["checks"]["file"]["status"] == "UP"


# --------------------------------------------------------------------- FTP
class TestFTP:
    @pytest.fixture()
    def server(self):
        server = MiniFTPServer()
        server.start()
        yield server
        server.close()

    def test_roundtrip_over_the_wire(self, server):
        fs = FTPFileSystem(port=server.port, user="u", password="p")
        fs.connect()
        try:
            fs.create("report.txt", "hello ftp")
            assert fs.read_text("report.txt") == "hello ftp"
            fs.append("report.txt", "!")
            assert fs.read_text("report.txt") == "hello ftp!"
            assert fs.stat("report.txt").size == 10
            assert fs.exists("report.txt")
            fs.rename("report.txt", "report2.txt")
            assert not fs.exists("report.txt")
            names = [i.name for i in fs.read_dir()]
            assert names == ["report2.txt"]
            fs.remove("report2.txt")
            assert not fs.exists("report2.txt")
            assert fs.health_check()["status"] == "UP"
        finally:
            fs.close()

    def test_rows_over_ftp(self, server):
        fs = FTPFileSystem(port=server.port)
        fs.connect()
        try:
            fs.create("data.csv", "a,b\n5,6\n")
            rows = list(fs.read_rows("data.csv"))
            assert rows == [{"a": "5", "b": "6"}]
        finally:
            fs.close()

    def test_health_down_after_server_gone(self, server):
        fs = FTPFileSystem(port=server.port)
        fs.connect()
        server.close()
        # kill the control socket server-side, then NOOP fails
        assert fs.health_check()["status"] in ("UP", "DOWN")  # may lag one call
        fs.close()
        assert fs.health_check()["status"] == "DOWN"


class TestSFTP:
    def test_requires_injected_client(self):
        fs = SFTPFileSystem()
        with pytest.raises(FileError, match="injected client"):
            fs.connect()

    def test_injected_fake_client(self):
        class FakeSFTP:
            def __init__(self):
                self.blobs = {}

            def putfo(self, fobj, path):
                self.blobs[path] = fobj.read()

            def getfo(self, path, fobj):
                fobj.write(self.blobs[path])

            def listdir(self, path):
                return sorted(self.blobs)

            def remove(self, path):
                del self.blobs[path]

            def rename(self, old, new):
                self.blobs[new] = self.blobs.pop(old)

        fs = SFTPFileSystem(client=FakeSFTP())
        fs.connect()
        fs.create("w.bin", b"123")
        assert fs.read("w.bin") == b"123"
        fs.rename("w.bin", "w2.bin")
        assert [i.name for i in fs.read_dir()] == ["w2.bin"]
        fs.remove("w2.bin")
        assert fs.health_check()["status"] == "UP"
