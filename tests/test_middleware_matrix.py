"""Middleware interaction matrix + error-path coverage (VERDICT r2
weak #9: auth edge cases, middleware interactions, and error paths).

Every test boots the REAL app over a real socket (AppRunner) so the
full onion — tracer → logging → CORS → metrics → auth — is exercised
in composition, not in isolation.
"""

import base64
import json

from gofr_tpu.http.errors import HTTPError

from .apputil import AppRunner


def _basic(user: str, pw: str) -> dict:
    token = base64.b64encode(f"{user}:{pw}".encode()).decode()
    return {"Authorization": f"Basic {token}"}


def _auth_runner(**extra) -> AppRunner:
    def build(app):
        app.enable_basic_auth(ada="pw")
        app.get("/secret", lambda ctx: {"ok": True})
        app.post("/echo", lambda ctx: ctx.bind())
    return AppRunner(build=build, config=extra or None)


class TestAuthComposition:
    def test_cors_preflight_bypasses_auth(self):
        """OPTIONS preflight must succeed without credentials — a
        browser cannot attach them preflight (reference middleware
        ordering: CORS before auth)."""
        with _auth_runner() as r:
            status, headers, _ = r.request(
                "OPTIONS", "/secret",
                headers={"Origin": "https://app.example",
                         "Access-Control-Request-Method": "GET"})
            assert status in (200, 204)
            assert "access-control-allow-origin" in {
                k.lower() for k in headers}

    def test_metrics_and_health_exempt_from_auth(self):
        with _auth_runner() as r:
            status, _, _ = r.request("GET", "/.well-known/health")
            assert status == 200
            status, _, _ = r.request("GET", "/.well-known/alive")
            assert status == 200

    def test_unauthorized_still_traced_and_counted(self):
        """A 401 must flow through metrics middleware (the request
        histogram counts rejects too)."""
        with _auth_runner() as r:
            status, _, _ = r.request("GET", "/secret")
            assert status == 401
            status, _, data = r.request("GET", "/secret",
                                        headers=_basic("ada", "pw"))
            assert status == 200
            scrape = r.request("GET", "/metrics",
                               port=r.metrics_port)[2].decode()
            assert "app_http_response" in scrape

    def test_auth_applies_to_every_verb(self):
        with _auth_runner() as r:
            status, _, _ = r.request("POST", "/echo", body={"x": 1})
            assert status == 401
            status, _, _ = r.request("POST", "/echo", body={"x": 1},
                                     headers=_basic("ada", "pw"))
            assert status == 201

    def test_garbage_authorization_headers(self):
        with _auth_runner() as r:
            for header in ("Basic", "Basic !!!", "Bearer abc",
                           "Basic " + "A" * 10000):
                status, _, _ = r.request(
                    "GET", "/secret", headers={"Authorization": header})
                assert status == 401, header


class TestErrorPaths:
    def test_malformed_json_body_is_400_not_500(self):
        with AppRunner() as r:
            r.app.post("/echo", lambda ctx: ctx.bind())
            status, _, data = r.request(
                "POST", "/echo", body=b"{not json",
                headers={"Content-Type": "application/json"})
            assert 400 <= status < 500

    def test_handler_http_error_maps_status_and_envelope(self):
        with AppRunner() as r:
            def teapot(ctx):
                raise HTTPError("short and stout", status_code=418)
            r.app.get("/teapot", teapot)
            status, _, data = r.request("GET", "/teapot")
            assert status == 418
            assert "short and stout" in json.loads(data)["error"]["message"]

    def test_unknown_route_404_envelope(self):
        with AppRunner() as r:
            status, _, data = r.request("GET", "/nope")
            assert status == 404
            assert "error" in json.loads(data)

    def test_method_not_allowed_405(self):
        with AppRunner() as r:
            r.app.get("/only-get", lambda ctx: "x")
            status, _, _ = r.request("DELETE", "/only-get")
            assert status == 405

    def test_head_mirrors_get_without_body(self):
        with AppRunner() as r:
            r.app.get("/data", lambda ctx: {"k": "v"})
            status, headers, data = r.request("HEAD", "/data")
            assert status == 200
            assert data in (b"", None)

    def test_oversized_headers_rejected(self):
        with AppRunner() as r:
            r.app.get("/x", lambda ctx: "ok")
            status, _, _ = r.request(
                "GET", "/x", headers={"X-Big": "v" * (70 * 1024)})
            assert status == 431

    def test_traceparent_roundtrip_on_errors(self):
        """Even a 500 reply carries the request's trace id."""
        with AppRunner() as r:
            def boom(ctx):
                raise RuntimeError("kaboom")
            r.app.get("/boom", boom)
            trace_id = "0af7651916cd43dd8448eb211c80319c"
            status, headers, _ = r.request(
                "GET", "/boom",
                headers={"traceparent":
                         f"00-{trace_id}-b7ad6b7169203331-01"})
            assert status == 500
            lower = {k.lower(): v for k, v in headers.items()}
            assert lower["x-trace-id"] == trace_id
