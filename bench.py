"""Serving benchmark: continuous-batching /chat throughput on real TPU.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...}.

Defensive against a flaky TPU backend (the axon plugin has been
observed to hang >120 s at initialization, or return UNAVAILABLE): the
parent process never touches JAX.  It probes the backend in a bounded,
retried subprocess, runs the measured bench in another bounded
subprocess, and falls back to a labeled CPU run if the TPU is
unreachable.  Whatever happens, exactly one JSON line reaches stdout —
on total failure it carries value 0.0 and an "error" field.

Scenario (BASELINE.json config 3, scaled to the available hardware):
Llama-3.2-1B-architecture model (random weights), N concurrent chat
requests with 64-token prompts and 32 generated tokens each, through
the continuous-batching engine (bucketed prefill + fixed-shape donated
decode + fused in-graph sampling).  vs_baseline is measured against the
north-star target of 2,000 req/s (which assumes a v5e-8; this runs on
however many chips are visible — one in CI).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys

# jax-free import: the shared compile-cache config path (the parent
# process must never touch JAX itself — see module docstring)
from gofr_tpu.config.env import (COMPILE_CACHE_ENV,
                                 resolve_compile_cache_dir)

PROBE_TIMEOUT_S = int(os.environ.get("GOFR_BENCH_PROBE_TIMEOUT", "600"))
PROBE_RETRIES = 2
TPU_BENCH_TIMEOUT_S = int(os.environ.get("GOFR_BENCH_TPU_TIMEOUT", "1200"))
CPU_BENCH_TIMEOUT_S = int(os.environ.get("GOFR_BENCH_CPU_TIMEOUT", "600"))


def _trunc(s: str, n: int = 200) -> str:
    """Bench artifacts embed error strings at most this long — a JAX
    traceback pasted whole made earlier BENCH_*.json files unreadable."""
    s = str(s)
    return s if len(s) <= n else s[:n - 1] + "…"


# ---------------------------------------------------------------- child

def _child_env(platform: str) -> dict:
    env = dict(os.environ)
    if platform == "cpu":
        env["JAX_PLATFORMS"] = "cpu"
    else:
        env.pop("JAX_PLATFORMS", None)
    env["GOFR_TELEMETRY"] = "false"
    # every child shares ONE persistent compile-cache dir (resolved
    # from the same config path the engine and TPU jobs use), so the
    # second child's warmup is cache hits, not recompiles
    env.setdefault(COMPILE_CACHE_ENV,
                   resolve_compile_cache_dir() or "off")
    return env


def _run_child(code: str, platform: str, timeout_s: int):
    """Run python -c code; return (rc, stdout, stderr) or (None,..) on timeout."""
    try:
        p = subprocess.run([sys.executable, "-c", code],
                           env=_child_env(platform), capture_output=True,
                           text=True, timeout=timeout_s,
                           cwd=os.path.dirname(os.path.abspath(__file__)))
        return p.returncode, p.stdout, p.stderr
    except subprocess.TimeoutExpired as e:
        out = e.stdout.decode() if isinstance(e.stdout, bytes) else (e.stdout or "")
        err = e.stderr.decode() if isinstance(e.stderr, bytes) else (e.stderr or "")
        return None, out, err + f"\n[timeout after {timeout_s}s]"


# env var alone does not beat the axon plugin; config.update does
_PIN_PRELUDE = """
import os
import jax
if os.environ.get("JAX_PLATFORMS") == "cpu":
    jax.config.update("jax_platforms", "cpu")
from gofr_tpu.config.env import enable_compile_cache
enable_compile_cache()  # shared persistent XLA compile cache
"""

PROBE_CODE = _PIN_PRELUDE + """
d = jax.devices()
print("PROBE_OK", jax.default_backend(), len(d))
"""

BENCH_CODE = _PIN_PRELUDE + """
import json, statistics, sys, time
import jax.numpy as jnp

from gofr_tpu.models.llama import LlamaConfig, llama_init, param_count
from gofr_tpu.serving.engine import EngineConfig, SamplingParams
from gofr_tpu.serving.glue import llama_engine

backend = jax.default_backend()
on_accel = backend not in ("cpu",)
if on_accel:
    model_config = LlamaConfig.llama3_1b().scaled(max_seq=1024)
    # batch 32: decode streams all params once per K-step pass
    # regardless of batch, and the carry/window work removed the
    # batch-proportional cache waste — wider batches now amortise the
    # weight stream (the r5 sweep showed 32 > 16 even pre-fix)
    max_batch, n_requests = 32, 128
    prompt_len, gen_len = 64, 32
else:  # CI / CPU smoke: tiny everything
    model_config = LlamaConfig.tiny()
    max_batch, n_requests = 4, 8
    prompt_len, gen_len = 16, 8

t0 = time.time()
params = llama_init(jax.random.key(0), model_config)
jax.block_until_ready(params)
n_params = param_count(params)
print(f"# init {model_config.n_layers}L/{model_config.dim}d "
      f"({n_params/1e9:.2f}B params) in {time.time()-t0:.1f}s on {backend}",
      file=sys.stderr)

quant = os.environ.get("GOFR_BENCH_QUANT") or None


def run_scenario(engine_cfg, prompts, gen_len, warm_lens,
                 warm_chunked=False):
    engine = llama_engine(params, model_config, engine_cfg,
                          quantize=quant)
    t0 = time.time()
    engine.warmup(prompt_lens=warm_lens, chunked=warm_chunked)
    print(f"# warmup (compile) {time.time()-t0:.1f}s", file=sys.stderr)
    engine.start()
    engine.stats = {k: 0 if isinstance(v, int) else 0.0
                    for k, v in engine.stats.items()}
    engine.goodput.reset()  # measure this scenario's waste only
    if getattr(engine, "costs", None) is not None and engine.costs.enabled:
        engine.costs.reset()  # per-signature prices for this scenario
    sp = SamplingParams(temperature=0.0, max_new_tokens=gen_len)
    t0 = time.time()
    deadline = t0 + 300.0
    reqs = [engine.submit(p, sp) for p in prompts]
    while any(r.finished_at is None and r.error is None for r in reqs):
        if time.time() > deadline:
            # a wedged scenario must not eat the whole child budget
            # and take the headline JSON line down with it
            engine.stop()
            raise TimeoutError("scenario did not finish in 300s")
        time.sleep(0.001)
    wall = time.time() - t0
    stats = dict(engine.stats)
    stats["goodput"] = engine.goodput.summary()
    stats["costs"] = engine.costs.by_kind() \
        if getattr(engine, "costs", None) is not None \
        and engine.costs.enabled else None
    engine.stop()
    return reqs, wall, stats


def lat_stats(reqs):
    # p50/p95 TTFT and TPOT (per-request mean inter-token latency) in
    # ms for a finished scenario -- the perf trajectory tracks latency,
    # not just tok/s. (No triple-quoted docstring: this function lives
    # inside the BENCH_CODE string literal.)
    ok = [r for r in reqs if r.error is None]
    ttfts = sorted(r.ttft_ms for r in ok if r.ttft_ms is not None)
    tpots = sorted((r.finished_at - r.first_token_at) * 1000.0
                   / (len(r.generated) - 1)
                   for r in ok
                   if r.first_token_at is not None
                   and r.finished_at is not None
                   and len(r.generated) > 1)

    def pct(values, p):
        if not values:
            return -1.0
        return round(values[min(len(values) - 1,
                                int(p * len(values)))], 2)

    return {"p50_ttft_ms": pct(ttfts, 0.50),
            "p95_ttft_ms": pct(ttfts, 0.95),
            "p50_tpot_ms": pct(tpots, 0.50),
            "p95_tpot_ms": pct(tpots, 0.95)}


base_cfg = EngineConfig(max_batch=max_batch, max_seq=model_config.max_seq,
                        prefill_buckets=(64, 128, 256, 512), seed=0,
                        # prompt 64 + gen 32 keeps every live row under
                        # 128: windowed decode attention reads O(128)
                        # rows instead of O(max_seq) per step
                        decode_windows=(128, 256),
                        # group more short prompts per prefill call —
                        # [16, 64] rows feed the MXU better than [8, 64]
                        prefill_batch=16 if on_accel else 8,
                        # fused multi-pass decode: one dispatch yields
                        # K x M = 32 tokens — exactly gen_len on accel,
                        # so each request is ONE dispatch of decode.
                        # The CPU smoke's gen 8 fits a single K=8 pass
                        # already; M > 1 would only waste steps there.
                        decode_passes_per_dispatch=4 if on_accel else 1)
prompt = list(range(1, prompt_len + 1))
reqs, wall, stats = run_scenario(base_cfg, [prompt] * n_requests, gen_len,
                                 (prompt_len,))

ok = [r for r in reqs if r.error is None]
total_tokens = sum(len(r.generated) for r in ok)
req_per_s = len(ok) / wall
tok_per_s = total_tokens / wall
ttfts = sorted(r.ttft_ms for r in ok if r.ttft_ms is not None)
p50_ttft = statistics.median(ttfts) if ttfts else -1.0

# MFU: decode FLOPs ~= 2 * params per generated token (attention adds
# ~2% at these lengths), prefill FLOPs = 2 * params * prompt tokens
# (which already covers each request's first sampled token), against
# the chip's peak bf16 FLOPs over the measured wall time.
PEAK_FLOPS = {"TPU v5 lite": 197e12, "TPU v5": 459e12,
              "TPU v5p": 459e12, "TPU v4": 275e12, "TPU v6 lite": 918e12}
HBM_GBS = {"TPU v5 lite": 819, "TPU v5": 2765, "TPU v5p": 2765,
           "TPU v4": 1228, "TPU v6 lite": 1640}
kind = jax.devices()[0].device_kind if on_accel else ""
peak = next((v for k, v in sorted(PEAK_FLOPS.items(),
                                  key=lambda kv: -len(kv[0]))
             if kind.startswith(k)), None)
hbm = next((v for k, v in sorted(HBM_GBS.items(),
                                 key=lambda kv: -len(kv[0]))
            if kind.startswith(k)), None)
flops = 2.0 * n_params * ((total_tokens - len(ok)) + len(ok) * prompt_len)
mfu = round(flops / (wall * peak), 4) if peak else None
# decode roofline: HBM-bound — every decode pass streams all params
# once for up to max_batch tokens (bf16 = 2 B/param; int8 halves it)
bytes_per_param = {"int8": 1.0, "int4": 0.5}.get(quant, 2.0)
roof = (hbm * 1e9) / (bytes_per_param * n_params / max_batch) \
    if hbm else None
# decode_s counts in-flight spans (pipelined passes overlap prefill/
# host work), so the residual is clamped: it is true dead time only
host_s = round(max(0.0, wall - stats["prefill_s"] - stats["decode_s"]), 2)

print(f"# {len(ok)}/{n_requests} ok, wall={wall:.2f}s, "
      f"decode={tok_per_s:.0f} tok/s, p50 TTFT={p50_ttft:.1f}ms, "
      f"mfu={mfu}, phases={stats} host_s={host_s}",
      file=sys.stderr)

# batch-32 decode-overhead scenario: short prompt, long greedy
# generation, all 32 slots saturated, run at decode_steps_per_pass=1 —
# one dispatch per token, the regime where per-dispatch host overhead
# (the thing BENCH_r05 was bound by) dominates and kernels don't.
# Measured twice: the fused multi-pass dispatch (M=8, one dispatch per
# 8 tokens) and the single-pass path (M=1). Greedy outputs must be
# bit-identical; the tok/s ratio quantifies pure dispatch overhead,
# and h2d_transfers shows the steady-state upload count (event-bounded,
# not per-pass). On the pre-PR engine this workload measured 15.8k
# tok/s on the CPU smoke host; the device-resident state alone moved
# M=1 to ~24k (1.5x) with M=8 adding another ~12% on CPU (on TPU the
# per-dispatch saving is far larger — that's what the TPU jobs verify).
dec_batch = 32
dec_n = 64 if on_accel else 32
dec_gen = 32 if on_accel else 64
dec_prompt = list(range(3, 3 + (64 if on_accel else 8)))


def decode_cfg(m):
    return EngineConfig(
        max_batch=dec_batch, max_seq=model_config.max_seq,
        prefill_buckets=(64, 128, 256, 512) if on_accel else (16, 64),
        seed=0, decode_steps_per_pass=1,
        decode_passes_per_dispatch=m)


try:
    d8, d8_wall, d8_stats = run_scenario(
        decode_cfg(8), [dec_prompt] * dec_n, dec_gen, (len(dec_prompt),))
    d1, d1_wall, d1_stats = run_scenario(
        decode_cfg(1), [dec_prompt] * dec_n, dec_gen, (len(dec_prompt),))
    ok8 = [r for r in d8 if r.error is None]
    ok1 = [r for r in d1 if r.error is None]
    assert len(ok8) == len(ok1) == dec_n, (len(ok8), len(ok1))
    assert [r.generated for r in ok8] == [r.generated for r in ok1], \
        "fused multi-pass decode diverged from the single-pass path"
    tok8 = sum(len(r.generated) for r in ok8) / d8_wall
    tok1 = sum(len(r.generated) for r in ok1) / d1_wall
    decode_payload = {
        "config": f"max_batch={dec_batch}, K=1, greedy, gen={dec_gen}",
        "latency_fused": lat_stats(d8),
        "latency_single": lat_stats(d1),
        "tok_per_s_fused_m8": round(tok8, 1),
        "tok_per_s_single": round(tok1, 1),
        "multi_pass_speedup": round(tok8 / tok1, 3),
        "greedy_identical": True,
        "fused": {k: round(v, 3) if isinstance(v, float) else v
                  for k, v in d8_stats.items()
                  if k in ("decode_passes", "decode_s", "dispatch_s",
                           "collect_s", "h2d_transfers", "sched_syncs")},
        "single": {k: round(v, 3) if isinstance(v, float) else v
                   for k, v in d1_stats.items()
                   if k in ("decode_passes", "decode_s", "dispatch_s",
                            "collect_s", "h2d_transfers",
                            "sched_syncs")},
    }
except Exception as exc:  # the headline number must survive this
    decode_payload = {"error": f"{type(exc).__name__}: {exc}"[:200]}
print(f"# decode-overhead: {decode_payload}", file=sys.stderr)

# prefill-TTFT scenario: long prompts (>= 4 bucket-width chunks) with
# a shared prefix, through the paged chunk walk — the ragged chunk
# KERNEL path (pages read in place; 'interpret' on the CPU smoke host,
# the real kernel on TPU) against the 'view' gather path that
# materialises a dense per-slot [Mp*pg] view of the pool every chunk.
# The pool allocation is max_seq=1024 rows/slot while each chunk only
# needs O(history+chunk), so the view path's O(allocation) HBM traffic
# is what this measures. Greedy outputs must be bit-identical; the
# kernel path must not be slower (prefill tok/s >= view) — both
# asserted in-bench, so a regression kills the scenario payload, not
# the headline.
pf_bucket = 64 if on_accel else 16
pf_n = 16 if on_accel else 8
pf_shared = [7] * (128 if on_accel else 32)  # 2 pages of shared head
pf_prompts = [pf_shared + list(range(100 + 4 * pf_bucket * i,
                                     100 + 4 * pf_bucket * (i + 1)))
              for i in range(pf_n)]  # >= 4 chunks past the shared head


def prefill_cfg(mode):
    return EngineConfig(max_batch=8 if on_accel else 4, max_seq=1024,
                        prefill_buckets=(pf_bucket,), seed=0,
                        kv_layout="paged",
                        page_size=64 if on_accel else 16,
                        prefix_cache=True, paged_attention=mode)


def prefill_run(mode):
    reqs, wall, stats = run_scenario(prefill_cfg(mode), pf_prompts,
                                     4, (pf_bucket,), warm_chunked=True)
    ok = [r for r in reqs if r.error is None]
    assert len(ok) == pf_n, [r.error for r in reqs]
    ptoks = sum(len(r.prompt_tokens) for r in ok)
    ttfts = sorted(r.ttft_ms for r in ok if r.ttft_ms is not None)
    return ([r.generated for r in ok],
            {"prefill_tok_per_s": round(ptoks / max(stats["prefill_s"],
                                                    1e-9), 1),
             "latency": lat_stats(reqs),
             "p50_ttft_ms": round(statistics.median(ttfts), 1),
             "prefill_calls": stats["prefill_calls"],
             "prefill_s": round(stats["prefill_s"], 3),
             "view_bytes_avoided": stats["view_bytes_avoided"]})


try:
    kernel_mode = "kernel" if on_accel else "interpret"
    k_toks, k_stats = prefill_run(kernel_mode)
    v_toks, v_stats = prefill_run("view")
    assert k_toks == v_toks, \
        "ragged chunk kernel diverged from the view path"
    ttft_payload = {
        "config": f"paged chunk walk, {pf_n} x "
                  f"{len(pf_prompts[0])}-token prompts "
                  f"({pf_bucket}-wide buckets), shared "
                  f"{len(pf_shared)}-token prefix, max_seq=1024",
        "kernel_impl": kernel_mode,
        "kernel": k_stats,
        "view": v_stats,
        "prefill_speedup": round(k_stats["prefill_tok_per_s"]
                                 / max(v_stats["prefill_tok_per_s"],
                                       1e-9), 3),
        "greedy_identical": True,
    }
    assert k_stats["prefill_tok_per_s"] >= v_stats["prefill_tok_per_s"], \
        f"kernel prefill slower than view path: {ttft_payload}"
except Exception as exc:  # the headline number must survive this
    ttft_payload = {"error": f"{type(exc).__name__}: {exc}"[:200]}
print(f"# prefill-ttft: {ttft_payload}", file=sys.stderr)

# production-shaped second scenario (VERDICT r4 #6): the full serving
# config — paged KV, prefix cache, speculative decode, max_batch=16
# (which clears pipeline_min_slots, so the decode pipeline engages) —
# on a shared-system-prompt workload, so engine-path regressions that
# the minimal smoke config cannot see surface round-over-round.
page = 64 if on_accel else 16
prod_cfg = EngineConfig(max_batch=16, max_seq=model_config.max_seq,
                        prefill_buckets=(64, 128, 256, 512), seed=0,
                        kv_layout="paged", page_size=page,
                        prefix_cache=True, speculative=True,
                        # drafting is only consulted at PASS boundaries
                        # (the matched tail ends at the boundary
                        # token), so the smoke run shrinks the pass and
                        # the n-gram to get deterministic engagement
                        # within its tiny token budget; accel keeps the
                        # throughput-shaped K=8 with 2-gram lookup
                        spec_ngram=2 if on_accel else 1,
                        decode_steps_per_pass=8 if on_accel else 2,
                        # windows the paged VIEW path's gather (the
                        # mesh/CPU path); the native kernel path is
                        # ragged already and ignores them
                        decode_windows=(256,) if on_accel else (64, 128))
# shared REPETITIVE system prompt spanning 3 full pages: the
# page-aligned prefix is cacheable (prefix_hits > 0) AND the prompt
# tail recurs earlier in the context, so prompt-lookup drafting
# actually engages (spec_passes > 0) — the old all-distinct system
# prompt measured speculative decoding without ever triggering it
# (VERDICT r5 weak #5)
pattern = [7, 11, 13, 17, 19, 23, 29, 31]
system = (pattern * ((3 * page) // len(pattern) + 1))[:3 * page]
prod_n = 64 if on_accel else 32
prod_gen = 32 if on_accel else 16
# per-request marker keeps continuations distinct; the prompt ends
# with the start of `pattern`, whose earlier occurrences feed the
# n-gram draft lookup from the very first decode pass
prod_prompts = [system + [1000 + i] + pattern[:3] for i in range(prod_n)]
try:
    preqs, pwall, pstats = run_scenario(
        prod_cfg, prod_prompts, prod_gen,
        (len(prod_prompts[0]),), warm_chunked=True)
    pok = [r for r in preqs if r.error is None]
    ptok = sum(len(r.generated) for r in pok)
    pttfts = sorted(r.ttft_ms for r in pok if r.ttft_ms is not None)
    prod_payload = {
        "req_per_s": round(len(pok) / pwall, 2),
        "tok_per_s": round(ptok / pwall, 1),
        "latency": lat_stats(preqs),
        "p50_ttft_ms": round(statistics.median(pttfts), 1) if pttfts else -1.0,
        "n_requests": prod_n,
        "config": "paged+prefix+spec+pipeline, max_batch=16",
        "prefix_hits": pstats.get("prefix_hits", 0),
        "spec_accepted": pstats.get("spec_accepted", 0),
        "spec_passes": pstats.get("spec_passes", 0),
        "decode_passes": pstats.get("decode_passes", 0),
        "goodput": pstats.get("goodput"),
    }
except Exception as exc:  # the headline number must survive this
    prod_payload = {"error": f"{type(exc).__name__}: {exc}"[:200]}
print(f"# prod-shaped: {prod_payload}", file=sys.stderr)
if not on_accel:
    # CPU smoke ENFORCES that the speculative path measured something:
    # a prod-shaped scenario reporting spec_passes=0 means the workload
    # never exercised what it claims to measure
    assert prod_payload.get("spec_passes", 0) > 0, (
        "prod-shaped smoke scenario never engaged speculative "
        f"decoding: {prod_payload}")

# kv-capacity scenario (quantized KV pages): at ONE fixed pool byte
# budget, how many resident sessions fit and what does decode run at,
# bf16 vs int8 KV (EngineConfig.kv_dtype)? Capacity is what int8 KV
# buys — per-row HBM drops from native-dtype*hd to hd+4 bytes — and
# the ratio is dtype arithmetic, so the CPU smoke can enforce it.
kv_sess_len = prompt_len + gen_len
kv_pages_per_sess = -(-kv_sess_len // page)
kv_row_native = (2 * model_config.n_layers * model_config.n_kv_heads
                 * model_config.head_dim
                 * jnp.dtype(model_config.dtype).itemsize)
# budget = exactly max_batch resident sessions at the NATIVE page cost
kv_budget = max_batch * kv_pages_per_sess * page * kv_row_native
kv_n = max_batch


def kv_run(dt):
    cfg = EngineConfig(max_batch=max_batch, max_seq=model_config.max_seq,
                       prefill_buckets=(64, 128, 256, 512), seed=0,
                       kv_layout="paged", page_size=page,
                       kv_dtype=dt, kv_pool_bytes=kv_budget)
    engine = llama_engine(params, model_config, cfg, quantize=quant)
    sessions = engine._n_pages // kv_pages_per_sess
    kv_bytes = engine.efficiency_state()["kv_bytes"]
    engine.warmup(prompt_lens=(prompt_len,))
    engine.start()
    sp = SamplingParams(temperature=0.0, max_new_tokens=gen_len)
    t0 = time.time()
    reqs = [engine.submit(prompt, sp) for _ in range(kv_n)]
    deadline = t0 + 300.0
    while any(r.finished_at is None and r.error is None for r in reqs):
        if time.time() > deadline:
            engine.stop()
            raise TimeoutError("kv-capacity run did not finish in 300s")
        time.sleep(0.001)
    wall = time.time() - t0
    engine.stop()
    toks = sum(len(r.generated) for r in reqs if r.error is None)
    return sessions, int(kv_bytes), round(toks / wall, 1)


try:
    kv_sess_b, kv_bytes_b, kv_tps_b = kv_run("bf16")
    kv_sess_i, kv_bytes_i, kv_tps_i = kv_run("int8")
    kv_payload = {
        "budget_bytes": int(kv_budget),
        "sessions_bf16": kv_sess_b, "sessions_int8": kv_sess_i,
        "capacity_ratio": round(kv_sess_i / max(1, kv_sess_b), 3),
        "tok_per_s_bf16": kv_tps_b, "tok_per_s_int8": kv_tps_i,
        "kv_bytes_bf16": kv_bytes_b, "kv_bytes_int8": kv_bytes_i,
    }
except Exception as exc:  # the headline number must survive this
    kv_payload = {"error": f"{type(exc).__name__}: {exc}"[:200]}
print(f"# kv-capacity: {kv_payload}", file=sys.stderr)
if not on_accel:
    # the capacity claim is deterministic dtype arithmetic (per-row
    # bytes native*hd vs hd+4): the CPU smoke enforces >= 1.8x so a
    # sizing regression kills the bench, not just a trajectory number
    assert kv_payload.get("capacity_ratio", 0.0) >= 1.8, (
        f"int8 KV pool holds < 1.8x the bf16 sessions: {kv_payload}")

# spec-decode scenario (adaptive speculation): single-slot greedy
# decode at decode_steps_per_pass=1 — the latency regime speculation
# exists for — on two workloads:
#   repetitive: every request is the same cyclic pattern, so the
#     n-gram index predicts continuations the model actually takes;
#   low-repetition (ADVERSARIAL): the prompt repeats a trigram marker
#     whose every occurrence continues differently, so drafts engage
#     but the model never confirms them — static drafting pays verify
#     rows for nothing, and the adaptive controller must drive
#     drafting ~off after pricing it.
# On CPU a verify pass costs ~width x a decode pass (compute scales
# with rows; there is no dispatch overhead to amortise), so WALL
# speedup is a TPU claim (scripts/tpu_jobs/11_spec_microprof.py).
# What the CPU smoke enforces instead is the dispatch-cost proxy:
# tokens per engine pass (each pass streams all weights once on TPU,
# verify width <= 16 rides the same memory-bound pass), plus the
# controller claims — less waste than static on the adversarial
# workload, near-zero tok/s regression — and greedy bit-identity
# across every spec/plain pair, with zero post-warmup recompiles.
sp_pattern = [7, 11, 13, 17, 19, 23, 29, 31]
sp_rep_prompts = [(sp_pattern * 8)[:61]] * (8 if on_accel else 4)
sp_marker = [41, 43, 47]
sp_low = []
sp_i = 0
while len(sp_low) < 58:  # marker recurs, continuations all diverge
    sp_low.extend(sp_marker)
    sp_low.extend([100 + (7 * sp_i) % 150 + j for j in range(4)])
    sp_i += 1
sp_low_prompts = [sp_low[:58] + sp_marker] * (8 if on_accel else 4)
sp_gen = 64 if on_accel else 48


def spec_cfg(spec, adaptive=True):
    return EngineConfig(max_batch=1, max_seq=256,
                        prefill_buckets=(64,), seed=0,
                        kv_layout="paged", page_size=page,
                        decode_steps_per_pass=1,
                        speculative=spec, spec_ngram=2,
                        spec_draft=4, spec_branches=2,
                        spec_adaptive=adaptive)


def spec_run(cfgv, prompts):
    reqs, wall, stats = run_scenario(cfgv, prompts, sp_gen, (64,),
                                     warm_chunked=True)
    ok = [r for r in reqs if r.error is None]
    assert len(ok) == len(prompts), [r.error for r in reqs]
    toks = sum(len(r.generated) for r in ok)
    passes = stats["decode_passes"] + stats["spec_passes"]
    drafted = stats.get("spec_drafted", 0)
    return {
        "gens": [list(r.generated) for r in ok],
        "tok_per_s": round(toks / wall, 1),
        # decode_s accumulates decode AND verify pass spans
        "decode_tok_per_s": round(toks / max(stats["decode_s"], 1e-9),
                                  1),
        "tok_per_pass": round(toks / max(passes, 1), 3),
        "spec_passes": stats["spec_passes"],
        "decode_passes": stats["decode_passes"],
        "accept_rate": round(stats.get("spec_accepted", 0)
                             / max(1, drafted), 3) if drafted else None,
        "spec_drafted": drafted,
        "recompiles": stats["recompiles"],
        "waste_spec_s": (stats.get("goodput") or {}).get(
            "waste_s", {}).get("spec_rejected", 0.0),
    }


try:
    sp_off_rep = spec_run(spec_cfg(False), sp_rep_prompts)
    sp_static_rep = spec_run(spec_cfg(True, adaptive=False),
                             sp_rep_prompts)
    sp_off_low = spec_run(spec_cfg(False), sp_low_prompts)
    sp_static_low = spec_run(spec_cfg(True, adaptive=False),
                             sp_low_prompts)
    sp_adapt_low = spec_run(spec_cfg(True, adaptive=True),
                            sp_low_prompts)
    for name, run_ in (("static_rep", sp_static_rep),
                       ("static_low", sp_static_low),
                       ("adaptive_low", sp_adapt_low)):
        base = sp_off_rep if name.endswith("rep") else sp_off_low
        assert run_["gens"] == base["gens"], \
            f"greedy speculative output diverged from plain ({name})"
        assert run_["recompiles"] == 0, \
            f"post-warmup recompile in spec run ({name})"
    spec_payload = {
        "config": "max_batch=1, K=1, greedy, ngram=2, draft=4, "
                  "branches=2, paged KV",
        "greedy_identical": True,
        "repetitive": {"off": {k: v for k, v in sp_off_rep.items()
                               if k != "gens"},
                       "static": {k: v for k, v in sp_static_rep.items()
                                  if k != "gens"}},
        "low_repetition": {"off": {k: v for k, v in sp_off_low.items()
                                   if k != "gens"},
                           "static": {k: v for k, v in
                                      sp_static_low.items()
                                      if k != "gens"},
                           "adaptive": {k: v for k, v in
                                        sp_adapt_low.items()
                                        if k != "gens"}},
        # tokens-per-pass ratio on the repetitive workload: the
        # dispatch-cost proxy the TPU wall speedup follows
        "tok_per_pass_ratio": round(sp_static_rep["tok_per_pass"]
                                    / max(sp_off_rep["tok_per_pass"],
                                          1e-9), 3),
        # adaptive regression on the adversarial workload, decode-span
        # based (wall includes prefill noise)
        "adaptive_regression": round(sp_adapt_low["decode_tok_per_s"]
                                     / max(sp_off_low[
                                         "decode_tok_per_s"], 1e-9),
                                     3),
    }
except Exception as exc:  # the headline number must survive this
    spec_payload = {"error": f"{type(exc).__name__}: {exc}"[:200]}
print(f"# spec-decode: {spec_payload}", file=sys.stderr)
if not on_accel and "error" not in spec_payload:
    # the pass-efficiency claim is deterministic at fixed seed: the
    # repetitive workload's drafts must fold >= 1.3 tokens into each
    # engine pass where plain decode folds exactly 1
    assert spec_payload["tok_per_pass_ratio"] >= 1.3, (
        f"speculation folded too few tokens per pass: {spec_payload}")
    # static drafting must have engaged on BOTH workloads (else the
    # adversarial comparison below measures nothing)
    assert sp_static_rep["spec_passes"] > 0, spec_payload
    assert sp_static_low["spec_drafted"] > 0, spec_payload
    # the controller's whole point: on the adversarial workload it
    # stops paying for rejected drafts (strictly less spec_rejected
    # waste than the static policy) without giving up decode speed
    assert (sp_adapt_low["waste_spec_s"]
            < sp_static_low["waste_spec_s"]), (
        f"adaptive controller wasted no less than static: "
        f"{spec_payload}")
    assert spec_payload["adaptive_regression"] >= 0.9, (
        f"adaptive speculation dragged decode down: {spec_payload}")

print("BENCH_JSON " + json.dumps({
    "metric": "chat_req_per_s",
    "value": round(req_per_s, 2),
    "unit": "req/s",
    "vs_baseline": round(req_per_s / 2000.0, 4),
    "tok_per_s": round(tok_per_s, 1),
    "p50_ttft_ms": round(p50_ttft, 1),
    "latency": lat_stats(reqs),
    "mfu": mfu,
    "roofline_tok_per_s": round(roof, 1) if roof else None,
    "pct_of_roofline": round(100 * tok_per_s / roof, 1) if roof else None,
    "phases": {"prefill_s": round(stats["prefill_s"], 2),
               "prefill_calls": stats["prefill_calls"],
               "decode_s": round(stats["decode_s"], 2),
               "decode_passes": stats["decode_passes"],
               "dispatch_s": round(stats["dispatch_s"], 3),
               "collect_s": round(stats["collect_s"], 3),
               "h2d_transfers": stats["h2d_transfers"],
               "sched_syncs": stats["sched_syncs"],
               "host_s": host_s},
    # device-time waste attribution for the headline scenario: the
    # goodput ratio plus the per-cause seconds (padding rows, bubbles,
    # preemption recompute, rejected speculation) — the 2.8%-MFU
    # question "where did the other device-seconds go", answered per run
    "goodput": stats.get("goodput"),
    # per-kind pass prices (us/token) from the cost observatory:
    # report-only context for the trajectory, never a gate
    "costs": stats.get("costs"),
    "platform": backend,
    "quantize": quant,
    "compile_cache_dir": jax.config.jax_compilation_cache_dir,
    "n_requests": n_requests,
    "decode_overhead": decode_payload,
    "prefill_ttft": ttft_payload,
    "prod_shaped": prod_payload,
    "kv_capacity": kv_payload,
    "spec_decode": spec_payload,
}))
"""


# ------------------------------------------------------- perf ledger

TRAJECTORY_FILE = "BENCH_TRAJECTORY.jsonl"


def headline_metrics(payload: dict) -> dict:
    """Flatten the per-scenario headline numbers out of a bench
    payload — the stable metric set the perf ledger tracks run over
    run and scripts/bench_compare.py gates on. Scenarios that errored
    simply contribute nothing (their keys are absent, not zero)."""
    out: dict = {}

    def put(key, value):
        if isinstance(value, (int, float)) and value >= 0:
            out[key] = round(float(value), 3)

    put("chat_req_per_s", payload.get("value"))
    put("chat_tok_per_s", payload.get("tok_per_s"))
    lat = payload.get("latency") or {}
    for k in ("p50_ttft_ms", "p95_ttft_ms", "p50_tpot_ms",
              "p95_tpot_ms"):
        put(k, lat.get(k))
    dec = payload.get("decode_overhead") or {}
    put("decode_tok_per_s_fused", dec.get("tok_per_s_fused_m8"))
    put("decode_tok_per_s_single", dec.get("tok_per_s_single"))
    pf = payload.get("prefill_ttft") or {}
    put("prefill_tok_per_s_kernel",
        (pf.get("kernel") or {}).get("prefill_tok_per_s"))
    put("prefill_tok_per_s_view",
        (pf.get("view") or {}).get("prefill_tok_per_s"))
    put("prefill_p50_ttft_ms", (pf.get("kernel") or {}).get("p50_ttft_ms"))
    prod = payload.get("prod_shaped") or {}
    put("prod_tok_per_s", prod.get("tok_per_s"))
    put("prod_req_per_s", prod.get("req_per_s"))
    # kv_* keys are capacity numbers, not throughput: bench_compare
    # reports them but never gates (not in THROUGHPUT_KEYS, not *_ms)
    kvc = payload.get("kv_capacity") or {}
    put("kv_sessions_bf16", kvc.get("sessions_bf16"))
    put("kv_sessions_int8", kvc.get("sessions_int8"))
    put("kv_capacity_ratio", kvc.get("capacity_ratio"))
    put("kv_tok_per_s_bf16", kvc.get("tok_per_s_bf16"))
    put("kv_tok_per_s_int8", kvc.get("tok_per_s_int8"))
    # spec_* keys are speculation diagnostics, not throughput:
    # bench_compare reports them but never gates (not in
    # THROUGHPUT_KEYS, not *_ms) — accept rates and pass-efficiency
    # ratios are workload properties, not perf trajectory
    spec = payload.get("spec_decode") or {}
    put("spec_tok_per_pass_ratio", spec.get("tok_per_pass_ratio"))
    put("spec_adaptive_regression", spec.get("adaptive_regression"))
    rep = (spec.get("repetitive") or {}).get("static") or {}
    put("spec_accept_rate_rep", rep.get("accept_rate"))
    low = spec.get("low_repetition") or {}
    put("spec_accept_rate_low",
        (low.get("static") or {}).get("accept_rate"))
    put("spec_waste_static_s",
        (low.get("static") or {}).get("waste_spec_s"))
    put("spec_waste_adaptive_s",
        (low.get("adaptive") or {}).get("waste_spec_s"))
    goodput = payload.get("goodput") or {}
    put("goodput_ratio", goodput.get("goodput_ratio"))
    # busy_s rides along so the compare gate can tell a statistically
    # meaningful goodput_ratio from same-host CPU-smoke noise (~20 ms
    # of busy time) — reported, never gated itself
    put("goodput_busy_s", goodput.get("busy_s"))
    for cause, seconds in (goodput.get("waste_s") or {}).items():
        put(f"waste_{cause}_s", seconds)
    # cost_* keys are per-kind µs/token prices from the pass-cost
    # observatory: bench_compare reports them but never gates (not in
    # THROUGHPUT_KEYS, not *_ms) — prices move with host load and
    # shape mix, so they ride the trajectory for context only
    for kind, us_per_token in (payload.get("costs") or {}).items():
        put(f"cost_{kind}_us_per_token", us_per_token)
    return out


def _append_trajectory(payload: dict) -> None:
    """Append this run's headline numbers (plus provenance) to the
    BENCH_TRAJECTORY.jsonl time series next to this file. The ledger
    is append-only and best-effort: a write failure must never take
    down the bench's stdout contract."""
    try:
        import platform as _platform
        import time as _time
        rec = {
            "ts": round(_time.time(), 3),
            "host": _platform.node(),
            "status": payload.get("status") or
                      ("cached" if payload.get("cached") else "unknown"),
            "platform": payload.get("platform"),
            "quantize": payload.get("quantize"),
            "metrics": headline_metrics(payload),
        }
        if payload.get("error"):
            rec["error"] = _trunc(payload["error"])
        path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            TRAJECTORY_FILE)
        with open(path, "a") as f:
            f.write(json.dumps(rec) + "\n")
        print(f"# trajectory: appended {rec['status']}/"
              f"{rec['platform']} entry to {TRAJECTORY_FILE}",
              file=sys.stderr)
    except Exception as exc:  # pragma: no cover - ledger is advisory
        print(f"# trajectory append failed: {exc!r}", file=sys.stderr)


# --------------------------------------------------------------- parent

def _probe(platform: str) -> bool:
    """True iff a backend of the *requested* platform initializes in time."""
    for attempt in range(PROBE_RETRIES):
        rc, out, err = _run_child(PROBE_CODE, platform, PROBE_TIMEOUT_S)
        tokens = out.split()
        probed = tokens[tokens.index("PROBE_OK") + 1] if "PROBE_OK" in tokens else ""
        want_cpu = platform == "cpu"
        if rc == 0 and probed and (probed == "cpu") == want_cpu:
            print(f"# probe[{platform}] ok: {out.strip().splitlines()[-1]}",
                  file=sys.stderr)
            return True
        print(f"# probe[{platform}] attempt {attempt + 1} failed rc={rc}: "
              f"{(err or out).strip().splitlines()[-1] if (err or out).strip() else '?'}",
              file=sys.stderr)
    return False


def _bench(platform: str, timeout_s: int):
    """Run the bench child; return (payload|None, error_line)."""
    rc, out, err = _run_child(BENCH_CODE, platform, timeout_s)
    for line in reversed(out.splitlines()):
        if line.startswith("BENCH_JSON "):
            return json.loads(line[len("BENCH_JSON "):]), ""
    # keep the last progress markers so a timeout says which stage hung
    tail = [_trunc(ln) for ln in (err or out).strip().splitlines()
            if ln][-3:]
    return None, _trunc(f"rc={rc}: "
                        f"{' | '.join(tail) if tail else 'no output'}")


def _cached_tpu_result():
    """Newest real-TPU bench payload landed by the background worker
    (scripts/tpu_worker.py drains scripts/tpu_queue/ whenever the flaky
    tunnel comes up during the round). A measured-earlier TPU number
    beats a fresh CPU fallback — but only a RECENT one: results older
    than GOFR_BENCH_CACHE_MAX_AGE_S (default 12 h, one round) predate
    the code under test and are ignored."""
    import time as _time
    max_age_s = float(os.environ.get("GOFR_BENCH_CACHE_MAX_AGE_S",
                                     str(12 * 3600)))
    results_dir = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                               "scripts", "tpu_results")
    best = None
    try:
        names = sorted(os.listdir(results_dir))
    except OSError:
        return None
    for name in names:
        if not name.endswith(".json"):
            continue
        try:
            with open(os.path.join(results_dir, name)) as f:
                rec = json.load(f)
            for line in reversed((rec.get("stdout") or "").splitlines()):
                line = line.strip()
                if not line.startswith("{"):
                    continue
                payload = json.loads(line)
                age_ok = _time.time() - rec.get("ts", 0) <= max_age_s
                # the cached run must match THIS run's quantization
                # mode — a bf16 payload must never stand in for an
                # int8 headline (or vice versa)
                quant_ok = payload.get("quantize") == (
                    os.environ.get("GOFR_BENCH_QUANT") or None)
                if payload.get("platform") == "tpu" \
                        and payload.get("value", 0) > 0 \
                        and age_ok and quant_ok:
                    if best is None or rec.get("ts", 0) > best[1]:
                        best = (payload, rec.get("ts", 0), name)
                break
        except (ValueError, OSError):
            continue
    if best is None:
        return None
    payload, ts, name = best
    # provenance hygiene: a cached payload may carry the stderr tail /
    # diagnostics of the RUN THAT PRODUCED IT — r5's cached result
    # spliced a long-fixed Mosaic compile error into a healthy round.
    # Stale run noise never rides into today's report.
    for stale in ("tail", "stderr", "fallback_reason", "fresh_cpu",
                  "status", "error"):
        payload.pop(stale, None)
    payload["cached"] = True
    payload["measured_at"] = ts
    payload["cached_age_s"] = round(_time.time() - ts, 1)
    payload["cache_source"] = name
    return payload


def main() -> None:
    errors = []
    payload = None

    want = os.environ.get("GOFR_BENCH_PLATFORM", "")
    plans = []
    if want:
        plans = [(want,
                  CPU_BENCH_TIMEOUT_S if want == "cpu" else TPU_BENCH_TIMEOUT_S)]
    else:
        if _probe("tpu"):
            # the axon tunnel has been observed to hang indefinitely at
            # backend init in SOME processes while a fresh process
            # connects fine — a second attempt is cheap insurance
            plans.append(("tpu", TPU_BENCH_TIMEOUT_S))
            plans.append(("tpu", TPU_BENCH_TIMEOUT_S))
        else:
            errors.append("tpu: backend probe failed/timed out")
            cached = _cached_tpu_result()
            if cached is not None:
                # the tunnel is down NOW, but the worker landed a real
                # TPU run earlier in the round — report that, PLUS a
                # fresh CPU run of the code actually under test (the
                # cached number may predate it within the age window)
                cached["status"] = "cached"
                cached["fallback_reason"] = "; ".join(errors)
                fresh, fresh_err = _bench("cpu", CPU_BENCH_TIMEOUT_S)
                cached["fresh_cpu"] = (fresh if fresh is not None
                                       else {"error": _trunc(fresh_err)})
                print(json.dumps(cached))
                _append_trajectory(cached)
                if fresh is not None:
                    # the fresh CPU sidecar is the number that tracks
                    # THIS code — it joins the ledger in its own right
                    fresh.setdefault("status", "fresh")
                    _append_trajectory(fresh)
                return
        plans.append(("cpu", CPU_BENCH_TIMEOUT_S))

    for platform, timeout_s in plans:
        payload, error = _bench(platform, timeout_s)
        if payload is not None:
            if platform == "cpu" and errors:
                # valid run, but degraded: label why the TPU path was skipped
                payload["status"] = "fallback"
                payload["fallback_reason"] = "; ".join(errors)
            else:
                payload["status"] = "fresh"
            break
        errors.append(_trunc(f"{platform}: {error}"))
        print(f"# bench[{platform}] failed: {error}", file=sys.stderr)

    if payload is None:
        # no measurement at all: say so AND exit nonzero — an rc-0 run
        # whose payload cannot be parsed reads as a healthy bench in
        # the round artifacts (BENCH_r05.json: rc 0, parsed null)
        payload = {"metric": "chat_req_per_s", "value": 0.0, "unit": "req/s",
                   "vs_baseline": 0.0, "status": "error",
                   "error": _trunc("; ".join(errors) or "unknown")}
        print(json.dumps(payload))
        _append_trajectory(payload)
        sys.exit(1)

    print(json.dumps(payload))
    _append_trajectory(payload)


if __name__ == "__main__":
    main()
