"""Serving benchmark: continuous-batching /chat throughput on real TPU.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Scenario (BASELINE.json config 3, scaled to the available hardware):
Llama-3.2-1B-architecture model (random weights), N concurrent chat
requests with 64-token prompts and 32 generated tokens each, through
the continuous-batching engine (bucketed prefill + fixed-shape donated
decode). vs_baseline is measured against the north-star target of
2,000 req/s (which assumes a v5e-8; this runs on however many chips
are visible — one in CI).
"""

from __future__ import annotations

import json
import statistics
import sys
import time


def main() -> None:
    import jax
    import jax.numpy as jnp

    from gofr_tpu.models.llama import LlamaConfig, llama_init
    from gofr_tpu.serving.engine import EngineConfig, SamplingParams
    from gofr_tpu.serving.glue import llama_engine

    on_tpu = jax.default_backend() == "tpu"
    if on_tpu:
        model_config = LlamaConfig.llama3_1b().scaled(max_seq=1024)
        max_batch, n_requests = 16, 64
        prompt_len, gen_len = 64, 32
    else:  # CI / CPU smoke: tiny everything
        model_config = LlamaConfig.tiny()
        max_batch, n_requests = 4, 8
        prompt_len, gen_len = 16, 8

    t0 = time.time()
    params = llama_init(jax.random.key(0), model_config)
    jax.block_until_ready(params)
    print(f"# init {model_config.n_layers}L/{model_config.dim}d params in "
          f"{time.time()-t0:.1f}s on {jax.default_backend()}", file=sys.stderr)

    engine = llama_engine(
        params, model_config,
        EngineConfig(max_batch=max_batch, max_seq=model_config.max_seq,
                     prefill_buckets=(64, 128, 256, 512)))
    engine.start()

    sp = SamplingParams(temperature=0.0, max_new_tokens=gen_len)
    prompt = list(range(1, prompt_len + 1))

    # warmup: compile prefill bucket + decode graph
    t0 = time.time()
    engine.submit_sync(prompt, sp)
    print(f"# warmup (compile) {time.time()-t0:.1f}s", file=sys.stderr)

    # measured run: n_requests submitted up front (saturated server)
    t0 = time.time()
    reqs = [engine.submit(prompt, sp) for _ in range(n_requests)]
    while any(r.finished_at is None and r.error is None for r in reqs):
        time.sleep(0.005)
    wall = time.time() - t0
    engine.stop()

    ok = [r for r in reqs if r.error is None]
    total_tokens = sum(len(r.generated) for r in ok)
    req_per_s = len(ok) / wall
    tok_per_s = total_tokens / wall
    ttfts = sorted(r.ttft_ms for r in ok if r.ttft_ms is not None)
    p50_ttft = statistics.median(ttfts) if ttfts else float("nan")

    print(f"# {len(ok)}/{n_requests} ok, wall={wall:.2f}s, "
          f"decode={tok_per_s:.0f} tok/s, p50 TTFT={p50_ttft:.1f}ms",
          file=sys.stderr)

    print(json.dumps({
        "metric": "chat_req_per_s",
        "value": round(req_per_s, 2),
        "unit": "req/s",
        "vs_baseline": round(req_per_s / 2000.0, 4),
    }))


if __name__ == "__main__":
    main()
